"""MatrixTable — 2-D dense distributed table with row-subset Get/Add and
sparse staleness tracking.

Reference capability (not copied): row-range-sharded dense matrix with
per-row or whole-table Get/Add (``src/table/matrix_table.cpp``), the gen-2
unified table with ``is_sparse`` per-worker × per-row ``up_to_date_``
staleness tracking so sparse Gets return only stale rows
(``src/table/matrix.cpp:517-572``), and the SparseMatrixTable wire
compression variant (``src/table/sparse_matrix_table.cpp``).

TPU-native re-design:

* Server state is ONE row-sharded ``jax.Array`` in HBM; row Get is a jitted
  device gather, row Add is a jitted scatter-add (linear updaters) or
  gather→apply→scatter (stateful updaters) — the client-side per-server
  ``Partition`` bucketing loop is gone, XLA partitions the scatter.
* Row-id batches are padded to power-of-two buckets aimed at a sentinel
  scratch row, so jit traces are reused across batch sizes and the MXU sees
  static shapes.
* ``up_to_date`` staleness tracking is host-side metadata (numpy bools):
  it gates *what crosses the host boundary*, which is exactly the resource it
  existed to save; wire compression (SparseFilter) only ever mattered on a
  host hop and lives in ``multiverso_tpu.utils.quantization``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu import log
from multiverso_tpu.dashboard import monitor
from multiverso_tpu.parallel import mesh as mesh_lib
from multiverso_tpu.runtime.zoo import Zoo
from multiverso_tpu.tables.base import ServerTable, WorkerTable
from multiverso_tpu.tables.array_table import _make_whole_update
from multiverso_tpu.updaters import AddOption, GetOption, SGDUpdater, Updater, get_updater
from multiverso_tpu.utils import async_upload, next_pow2 as _next_pow2


import functools


@functools.partial(jax.jit, static_argnames=("bucket", "cols"))
def _device_pad(values: jax.Array, bucket: int, cols: int) -> jax.Array:
    """(n, c) → (bucket, cols) zero-padded, entirely on device."""
    out = jnp.zeros((bucket, cols), values.dtype)
    return out.at[: values.shape[0], : values.shape[1]].set(values)


def _use_pallas_scatter(backend: str, num_shards: int) -> bool:
    """Pallas row-DMA scatter serves single-shard TPU tables only:
    pallas_call has no SPMD partitioning rule, so multi-device tables take
    XLA's scatter (which partitions fine)."""
    return backend == "tpu" and num_shards == 1


class MatrixServer(ServerTable):
    def __init__(self, num_row: int, num_col: int, dtype: Any = np.float32,
                 updater_type: str = "", num_workers: Optional[int] = None,
                 init_value: Optional[np.ndarray] = None,
                 init_range: Optional[Tuple[float, float]] = None,
                 seed: int = 0, is_sparse: bool = False,
                 is_pipelined: Optional[bool] = None) -> None:
        super().__init__()
        zoo = Zoo.instance()
        self.num_row = int(num_row)
        self.num_col = int(num_col)
        self.dtype = np.dtype(dtype)
        self.mesh = zoo.mesh
        self.num_workers = num_workers if num_workers is not None else zoo.num_workers
        num_shards = zoo.num_servers
        # Keep >=1 scratch row past num_row: padded id buckets aim there.
        self.padded_rows = mesh_lib.pad_to_multiple(self.num_row, num_shards)
        if self.padded_rows == self.num_row:
            self.padded_rows += num_shards
        self.sentinel_row = self.num_row
        # Pad cols to the 128-lane width: XLA's physical TPU layout already
        # tiles the minor dim to 128, so this costs no extra HBM — and it
        # unlocks the Pallas row-DMA scatter path (ops/pallas_rows), which
        # is ~8x faster than XLA's serialized scatter for row Adds.
        self.padded_cols = mesh_lib.pad_to_multiple(self.num_col, 128)

        sharding = mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=0)
        init = np.zeros((self.padded_rows, self.padded_cols), dtype=self.dtype)
        if init_value is not None:
            init[: self.num_row, : self.num_col] = np.asarray(
                init_value, dtype=self.dtype).reshape(self.num_row, self.num_col)
        elif init_range is not None:
            # random-init server ctor overload (reference: matrix_table.cpp:372-384)
            lo, hi = init_range
            rng = np.random.default_rng(seed)
            init[: self.num_row, : self.num_col] = rng.uniform(
                lo, hi, size=(self.num_row, self.num_col)).astype(self.dtype)
        self.data = jax.device_put(init, sharding)

        self.updater = get_updater(self.dtype, updater_type)
        worker_dim = self.num_workers if self.updater.per_worker_state else 1
        self.states: Dict[str, jax.Array] = {}
        for name, (shape_suffix, sdtype) in self.updater.state_spec(
                (self.padded_rows, self.padded_cols), self.dtype).items():
            s_shard = mesh_lib.table_sharding(self.mesh, ndim=3, shard_dim=1)
            self.states[name] = jax.device_put(
                np.zeros((worker_dim,) + tuple(shape_suffix), dtype=sdtype), s_shard)

        # staleness metadata (gen-2 `up_to_date_`): host-side control plane.
        # is_pipelined doubles the planes (reference matrix.cpp:407-418):
        # each worker owns TWO staleness identities — worker_id and
        # worker_id + num_workers — which its double-buffered client
        # alternates between, so an in-flight pipelined Get and the next Get
        # each track their own stale set.
        self.is_sparse = bool(is_sparse)
        if is_pipelined is None:
            from multiverso_tpu import config as config_mod
            is_pipelined = bool(config_mod.get_flag("is_pipelined"))
        self.is_pipelined = bool(is_pipelined)
        if self.is_sparse:
            self.num_slots = self.num_workers * (2 if self.is_pipelined else 1)
            self._up_to_date = np.zeros((self.num_slots, self.num_row), dtype=bool)
            self._std_lock = threading.Lock()

        self._whole_update = _make_whole_update(self.updater)
        self._linear = type(self.updater) in (Updater, SGDUpdater)
        self._sign = -1.0 if isinstance(self.updater, SGDUpdater) else 1.0
        self._gather = jax.jit(lambda data, ids: data[ids])
        # device-out gets feed WORKER-thread jits (the word2vec fast
        # path's compact training space): committed to ONE device so
        # those jits are single-device programs — concurrent sharded
        # executions from worker threads deadlock the CPU backend's
        # collective rendezvous while the dispatcher runs its own sharded
        # gather (the same decision, for the same reason, as
        # ArrayServer._leaf_codec; scatters re-shard on the way back in)
        from jax.sharding import SingleDeviceSharding
        _out_dev = SingleDeviceSharding(jax.devices()[0])
        self._gather_out = lambda data, ids: jax.device_put(
            self._gather(data, ids), _out_dev)
        self._pallas_scatter = _use_pallas_scatter(
            jax.default_backend(), num_shards)
        if self._pallas_scatter:
            from multiverso_tpu.ops.pallas_rows import scatter_add_rows
            # unique-id contract: see process_add
            self._scatter_add_raw = scatter_add_rows
            self._scatter_add = scatter_add_rows
        else:
            self._scatter_add_raw = lambda data, ids, delta: (
                data.at[ids].add(delta))
            self._scatter_add = jax.jit(self._scatter_add_raw,
                                        donate_argnums=(0,))
        self._row_update = self._make_row_update(self.updater)

    def _make_row_update(self, updater: Updater, jit: bool = True):
        def f(data, states, ids, delta, worker, scalars):
            rows = data[ids]
            if updater.per_worker_state:
                sliced = {k: v[worker, ids] for k, v in states.items()}
            else:
                sliced = {k: v[0, ids] for k, v in states.items()}
            new_rows, new_sliced = updater.apply(rows, sliced, delta, scalars)
            data = data.at[ids].set(new_rows)
            if updater.per_worker_state:
                new_states = {k: states[k].at[worker, ids].set(new_sliced[k]) for k in states}
            else:
                new_states = {k: states[k].at[0, ids].set(new_sliced[k]) for k in states}
            return data, new_states

        return jax.jit(f, donate_argnums=(0, 1)) if jit else f

    def row_apply_traceable(self):
        """The per-row update as a TRACEABLE function
        ``(data, states, ids, delta, worker, scalars) -> (data, states)``
        for embedding in a caller's fused jit (device transactions).
        Same semantics as the add path: linear updaters reduce to a
        scatter-add (sign folded in), stateful updaters run the row
        update. ``ids`` must be unique apart from sentinel pads with
        zero deltas."""
        if self._linear:
            sign, scatter = self._sign, self._scatter_add_raw

            def apply_linear(data, states, ids, delta, worker, scalars):
                return scatter(data, ids, sign * delta), states

            return apply_linear
        return self._make_row_update(self.updater, jit=False)

    # -- helpers -----------------------------------------------------------
    def _bucket_ids(self, ids: np.ndarray, values: Optional[np.ndarray],
                    ensure_pad: bool = False
                    ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], int]:
        """Pad (ids, values) to a power-of-two bucket aimed at the sentinel
        scratch row so jit traces are shape-stable. ``ensure_pad`` keeps at
        least one sentinel slot (device-out gets hand the bucket itself to
        the caller as a compact training space; its masked ops need a
        guaranteed non-live row)."""
        n = len(ids)
        # min bucket = pallas ROW_GROUP (batch must be a group multiple)
        from multiverso_tpu.ops.pallas_rows import ROW_GROUP
        bucket = max(_next_pow2(n + 1 if ensure_pad else n), ROW_GROUP)
        pad = bucket - n
        ids_p = np.concatenate([ids, np.full(pad, self.sentinel_row, dtype=ids.dtype)])
        vals_p = None
        if values is not None:
            padded = np.zeros((bucket, self.padded_cols), dtype=values.dtype)
            padded[:n, : self.num_col] = values
            vals_p = async_upload(padded)
        return async_upload(ids_p), vals_p, n

    # -- server ops --------------------------------------------------------
    def merge_add_requests(self, requests):
        """Fuse queued host row-Adds into ONE scatter: concatenate
        (ids, values) across the group and hand back one request whose
        apply is a single jitted/pallas scatter_add. Duplicate rows are
        pre-aggregated client-style INSIDE ``process_add`` (the shared
        ``remote.merge_duplicate_rows``) exactly when the apply path
        requires unique ids — the pallas in-place row-DMA kernel and
        stateful updaters; XLA's scatter-add handles duplicates natively,
        so the linear non-pallas path skips the host-side aggregation
        entirely. Linear updaters only — a stateful updater
        (momentum/adagrad) applied once to a summed delta is a different
        operator than N sequential applies. Whole-table, device-resident,
        and transact forms stop the scan (None when FIRST — per-message
        dispatch; otherwise the compatible prefix fuses and the rest
        waits for the next call). The ``apply_batch_rows`` flag bounds
        the fused row count so the power-of-two id bucket (and its
        zero-padded upload) cannot blow up under backlog."""
        if not self._linear:
            return None
        from multiverso_tpu import config as config_mod
        rows_cap = int(config_mod.get_flag("apply_batch_rows"))
        ids_list, vals_list = [], []
        total = 0
        for request in requests:
            if not (isinstance(request, tuple) and len(request) == 3):
                break
            row_ids, values, _option = request
            if row_ids is None or isinstance(values, jax.Array):
                break
            row_ids = np.asarray(row_ids, dtype=np.int32).reshape(-1)
            values = np.asarray(values, dtype=self.dtype).reshape(
                -1, self.num_col)
            if len(row_ids) != len(values):
                break  # per-message path reports the real error
            if ids_list and rows_cap > 0 \
                    and total + len(row_ids) > rows_cap:
                break
            ids_list.append(row_ids)
            vals_list.append(values)
            total += len(row_ids)
        if not ids_list:
            return None
        ids = np.concatenate(ids_list)
        return ((ids, np.concatenate(vals_list), requests[0][2]),
                int(len(ids)), len(ids_list))

    def process_add(self, request):
        if isinstance(request[0], str) and request[0] == "transact":
            return self._process_transact(request)
        if isinstance(request[0], str) and request[0] == "transact_named":
            return self._process_transact(self._resolve_named(request))
        row_ids, values, option = request
        option = option or AddOption()
        # administrative access (worker id -1) charges slot 0, not slot n-1
        worker, scalars = self._option_consts(option)
        if isinstance(values, jax.Array):
            # Device add (the LocalForward analog: an in-process worker's
            # delta never touches the host — reference local messages
            # skipped serialization the same way, communicator.cpp:93-105).
            # Caller contract: ids unique; pad slots aim at sentinel_row
            # with exactly-zero deltas.
            self._process_add_device(row_ids, values, option, worker, scalars)
            return
        if row_ids is None:
            delta = np.zeros((self.padded_rows, self.padded_cols), dtype=self.dtype)
            delta[: self.num_row, : self.num_col] = np.asarray(
                values, dtype=self.dtype).reshape(self.num_row, self.num_col)
            self.data, self.states = self._whole_update(
                self.data, self.states, async_upload(delta), worker,
                scalars)
            touched: Optional[np.ndarray] = None
        else:
            row_ids = np.asarray(row_ids, dtype=np.int32).reshape(-1)
            self._check_row_range(row_ids, "add")
            values = np.asarray(values, dtype=self.dtype).reshape(-1, self.num_col)
            if len(row_ids) != len(values):
                log.fatal("Matrix.add: %d ids but %d value rows", len(row_ids), len(values))
            # unique ids: required by stateful updaters (one apply per row)
            # and by the pallas scatter kernel's in-place row DMA contract;
            # XLA's scatter-add handles duplicates natively, so the linear
            # non-pallas path skips the host-side aggregation (fused
            # micro-batches from the dispatcher concatenate without
            # dedup for exactly this reason)
            if not (self._linear and not self._pallas_scatter):
                # lazy import: remote imports this module (worker proxies)
                from multiverso_tpu.runtime.remote import \
                    merge_duplicate_rows
                row_ids, values = merge_duplicate_rows(row_ids, values)
            ids_p, vals_p, _ = self._bucket_ids(row_ids, values)
            if self._linear:
                self.data = self._scatter_add(self.data, ids_p, self._sign * vals_p)
            else:
                self.data, self.states = self._row_update(
                    self.data, self.states, ids_p, vals_p, worker, scalars)
            touched = row_ids
        if self.is_sparse:
            with self._std_lock:
                if touched is None:
                    self._up_to_date[:, :] = False
                else:
                    self._up_to_date[:, touched] = False

    def _process_add_device(self, row_ids, values, option, worker,
                            scalars) -> None:
        row_ids = np.asarray(row_ids, dtype=np.int32).reshape(-1)
        n = len(row_ids)
        if values.shape[0] != n:
            log.fatal("Matrix.add(device): %d ids but %d value rows",
                      n, values.shape[0])
        from multiverso_tpu.ops.pallas_rows import ROW_GROUP
        bucket = max(_next_pow2(n), ROW_GROUP)
        ids_p = async_upload(np.concatenate(
            [row_ids, np.full(bucket - n, self.sentinel_row, np.int32)]))
        vals_p = _device_pad(values.astype(self.dtype), bucket,
                             self.padded_cols)
        # worker-thread kernels hand deltas back committed to ONE device
        # (the gather_out contract); re-shard here — on the dispatcher
        # thread, where cross-shard collectives are legal — or the
        # scatter jit would reject the mixed device sets
        vals_p = jax.device_put(
            vals_p, mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=0))
        if self._linear:
            self.data = self._scatter_add(self.data, ids_p,
                                          self._sign * vals_p)
        else:
            self.data, self.states = self._row_update(
                self.data, self.states, ids_p, vals_p, worker, scalars)
        if self.is_sparse:
            with self._std_lock:
                live = row_ids[row_ids < self.num_row]
                self._up_to_date[:, live] = False

    def _check_row_range(self, row_ids: np.ndarray, op: str) -> None:
        """Host-path ids must be in [0, num_row). Worker proxies already
        guard this, so only a routing bug (e.g. a shard router sending
        GLOBAL ids to a span-local member) reaches here — and it must die
        loudly: jax's clamping gather/scatter would otherwise silently
        misdirect the rows to the last local row."""
        if row_ids.size and (int(row_ids.min()) < 0
                             or int(row_ids.max()) >= self.num_row):
            log.fatal("Matrix.%s: row id out of range [0, %d) (offset %d "
                      "of the global table) — sharded routers must send "
                      "shard-local ids (docs/sharding.md)", op,
                      self.num_row, self.row_offset)

    def _resolve_named(self, request):
        """Rehydrate a named transaction descriptor into the live form:
        resolve the program name to this rank's locally-built jit and the
        table ids to this rank's server tables — the host-serializable
        indirection that lets device transactions ride the multihost
        lockstep stream (see runtime/programs.py)."""
        from multiverso_tpu.runtime.programs import resolve_program
        from multiverso_tpu.runtime.zoo import Zoo

        _, name, other_ids, args, touched = request
        server = Zoo.instance().server
        others = [server.table(tid)._unwrapped() for tid in other_ids]
        return ("transact", resolve_program(name), others, args, touched)

    def _process_transact(self, request):
        """Device transaction: ONE dispatcher op that reads several tables'
        device state, runs a caller-built fused jit over all of it, and
        writes the results back atomically (w.r.t. the dispatcher's
        serialization). The TPU-era answer to the reference's multi-table
        block protocols (pull rows from 2+ tables, train, push deltas —
        communicator.cpp RequestParameter/AddDeltaParameter): instead of
        2N messages and 2N+1 device dispatches, the whole block is one
        message and one dispatch with donated table buffers.

        request = ("transact", fn, other_servers, args, touched):
        ``fn(datas, states, *args) -> (new_datas, new_states, extra)``
        over lists ordered [this table, *other_servers]; ``extra`` is the
        reply (stays on device). ``touched`` (per-table id arrays or None)
        drives sparse-staleness invalidation."""
        _, fn, others, args, touched = request
        tables = [self] + list(others)
        datas = [t.data for t in tables]
        states = [t.states for t in tables]
        with monitor("SERVER_PROCESS_TRANSACT"):
            out = fn(datas, states, *args)
        try:
            new_datas, new_states, extra = out
            if (len(new_datas) != len(tables)
                    or len(new_states) != len(tables)):
                raise ValueError("result lists do not match table count")
        except (TypeError, ValueError) as exc:
            # the fn's jit has already executed and DONATED every table's
            # live buffers — there is nothing to roll back to. Die loudly
            # with the reason rather than serving dead buffers forever.
            log.fatal("transact fn must return (new_datas, new_states, "
                      "extra) matching the %d-table list (%s); the tables' "
                      "donated state is unrecoverable — recreate them",
                      len(tables), exc)
        for t, d, s in zip(tables, new_datas, new_states):
            t.data, t.states = d, s
        for t, ids in zip(tables, touched or [None] * len(tables)):
            if getattr(t, "is_sparse", False) and ids is not None:
                with t._std_lock:
                    live = ids[ids < t.num_row]
                    t._up_to_date[:, live] = False
        return extra

    def _is_worker(self, option) -> bool:
        """Administrative access (worker id outside [0, num_slots), e.g.
        checkpoint reads on a server-only node) must not touch any worker's
        staleness bitmap — aliasing it onto slot 0 would serve worker 0
        stale rows from its client cache (mirrors SyncServer._is_admin).
        num_slots covers the pipelined second plane (worker_id+num_workers)."""
        return option is not None and 0 <= option.worker_id < self.num_slots

    def process_get(self, request):
        device_out = False
        if len(request) == 3:  # in-process device-out form
            row_ids, option, device_out = request
        else:
            row_ids, option = request
        if row_ids is None:
            if self.is_sparse and self._is_worker(option):
                return self._sparse_get(option)
            # admin whole-table reads take the dense path
            out = self.updater.access(self.data)
            return self._host_read(out)[: self.num_row, : self.num_col]
        row_ids = np.asarray(row_ids, dtype=np.int32).reshape(-1)
        if not device_out:
            # device gets may carry sentinel-aimed pad ids (the compact
            # training space contract); host/wire gets may not
            self._check_row_range(row_ids, "get")
        ids_p, _, n = self._bucket_ids(row_ids, None, ensure_pad=device_out)
        gathered = (self._gather_out if device_out
                    else self._gather)(self.data, ids_p)
        if self.is_sparse and self._is_worker(option):
            with self._std_lock:
                self._up_to_date[option.worker_id, row_ids] = True
        if device_out:
            # rows stay in HBM: (bucket, padded_cols), slots >= n are
            # sentinel copies — the caller's compact training space
            return gathered
        return self._host_read(gathered)[:n, : self.num_col]

    def _sparse_get(self, option: GetOption):
        """Return only the rows stale for this worker: (ids, rows)."""
        w = option.worker_id
        with self._std_lock:
            stale = np.where(~self._up_to_date[w])[0].astype(np.int32)
            self._up_to_date[w, stale] = True
        if len(stale) == 0:
            return stale, np.zeros((0, self.num_col), dtype=self.dtype)
        if len(stale) == self.num_row:
            return stale, self._host_read(
                self.data)[: self.num_row, : self.num_col]
        ids_p, _, n = self._bucket_ids(stale, None)
        rows = self._host_read(
            self._gather(self.data, ids_p))[:n, : self.num_col]
        return stale, rows

    def remote_spec(self):
        return {"kind": "matrix", "num_row": self.num_row,
                "num_col": self.num_col, "dtype": self.dtype.str,
                "is_sparse": self.is_sparse,
                "is_pipelined": self.is_pipelined,
                "num_workers": self.num_workers}

    # -- checkpoint --------------------------------------------------------
    def store(self, stream) -> None:
        from multiverso_tpu.checkpoint import write_array, write_state_dict
        write_array(stream,
                    self._host_read(self.data)[: self.num_row,
                                               : self.num_col])
        # updater state sliced to logical dims (padding is a function of
        # the restoring mesh, not checkpoint content)
        write_state_dict(stream, {
            name: self._host_read(arr)[:, : self.num_row, : self.num_col]
            for name, arr in self.states.items()})

    def load(self, stream) -> None:
        from multiverso_tpu.checkpoint import read_array, read_state_dict
        arr = read_array(stream).astype(self.dtype).reshape(self.num_row, self.num_col)
        padded = np.zeros((self.padded_rows, self.padded_cols), dtype=self.dtype)
        padded[: self.num_row, : self.num_col] = arr
        self.data = jax.device_put(
            padded, mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=0))
        loaded = read_state_dict(stream)
        s_shard = mesh_lib.table_sharding(self.mesh, ndim=3, shard_dim=1)
        for name, cur in self.states.items():
            got = loaded.get(name)
            if got is None:
                continue  # v1 checkpoint: that state resets (pre-v2 behavior)
            if got.shape[0] != cur.shape[0]:
                # per-worker state from a world with a different worker
                # count: elastic restarts keep working — reset like v1
                log.info("checkpoint: %s worker dim %d != %d; resetting "
                         "that updater state", name, got.shape[0],
                         cur.shape[0])
                continue
            full = np.zeros(cur.shape, np.dtype(cur.dtype))
            full[:, : self.num_row, : self.num_col] = got
            self.states[name] = jax.device_put(full, s_shard)
        if self.is_sparse:
            # staleness is NOT restorable state: it certifies worker-side
            # client caches the snapshot does not cover — a restored
            # table must serve every row fresh once (values re-pulled,
            # resume-exactness preserved; claiming freshness against
            # unknown caches would serve stale rows silently)
            with self._std_lock:
                self._up_to_date[:, :] = False

    # -- live migration (shard/reshard.py) ---------------------------------
    def extract_range(self, lo: int, hi: int):
        """Raw values of shard-local rows [lo, hi) — the migration
        transfer unit. Updater state deliberately excluded (documented
        reset on migration, like a v1 checkpoint restore)."""
        return self._host_read(self.data)[lo:hi, : self.num_col]

    def absorb_range(self, start: int, values) -> None:
        """Install raw rows at [start, start+len) — the recipient side of
        extract_range. Bypasses updaters: migrated values are state, not
        gradients (an updater would rescale them)."""
        values = np.asarray(values, dtype=self.dtype)
        n = values.shape[0]
        if start < 0 or start + n > self.num_row:
            log.fatal("absorb_range [%d, %d) outside [0, %d)",
                      start, start + n, self.num_row)
        padded = np.array(self._host_read(self.data))
        padded[start:start + n, : self.num_col] = values
        self.data = jax.device_put(
            padded, mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=0))
        if self.is_sparse:
            with self._std_lock:
                self._up_to_date[:, start:start + n] = False


class MatrixWorker(WorkerTable):
    """Client proxy for a 2-D table: whole or row-subset Get/Add; in sparse
    mode keeps a local row cache refreshed with only-stale-rows Gets."""

    # in-process proxies exchange device arrays with the dispatcher; the
    # remote subclass overrides this (and the device methods) — callers
    # must branch on the flag, not on hasattr
    supports_device_io = True

    def __init__(self, num_row: int, num_col: int, dtype: Any = np.float32,
                 updater_type: str = "", init_value: Optional[np.ndarray] = None,
                 init_range: Optional[Tuple[float, float]] = None,
                 is_sparse: bool = False, seed: int = 0,
                 is_pipelined: Optional[bool] = None,
                 server: Optional[MatrixServer] = None) -> None:
        super().__init__()
        self.num_row = int(num_row)
        self.num_col = int(num_col)
        self.dtype = np.dtype(dtype)
        self.is_sparse = bool(is_sparse)
        self._server_table = server or MatrixServer(
            num_row, num_col, dtype, updater_type, init_value=init_value,
            init_range=init_range, seed=seed, is_sparse=is_sparse,
            is_pipelined=is_pipelined)
        self._register(self._server_table)
        if Zoo.instance().multihost is not None:
            # device IO exchanges jax.Arrays with the dispatcher; lockstep
            # descriptors must be host-serializable — host paths only
            self.supports_device_io = False
        self._init_client_state(self._server_table.is_pipelined
                                if self.is_sparse else False,
                                self._server_table.num_workers)

    def _init_client_state(self, pipelined: bool, num_workers: int) -> None:
        """Sparse-mode client caches: one per staleness plane. In pipelined
        mode whole-table Gets alternate planes so an in-flight prefetch and
        the next Get never consume each other's stale sets."""
        self._pipelined = bool(pipelined)
        self._num_workers = int(num_workers)
        self._n_phases = 2 if self._pipelined else 1
        self._caches = [np.zeros((self.num_row, self.num_col), self.dtype)
                        for _ in range(self._n_phases)] if self.is_sparse else []
        self._phase = 0
        self._phase_of: Dict[int, int] = {}  # msg_id -> phase (async gets)
        # observability: rows actually fetched from the server by this proxy
        # (the resource candidate-row pulls exist to bound — tests assert on it)
        self.rows_pulled = 0

    # -- get ---------------------------------------------------------------
    def get(self, row_ids: Optional[np.ndarray] = None,
            option: Optional[GetOption] = None) -> np.ndarray:
        option, phase = self._prep_get_option(option, row_ids)
        raw = super().get((self._norm_ids(row_ids), option))
        return self._finish_get(raw, row_ids, phase)

    def get_async(self, row_ids: Optional[np.ndarray] = None,
                  option: Optional[GetOption] = None) -> int:
        option, phase = self._prep_get_option(option, row_ids)
        msg_id = super().get_async((self._norm_ids(row_ids), option))
        self._phase_of[msg_id] = phase
        return msg_id

    def process_reply_get(self, raw, request):
        return raw

    def wait_get(self, msg_id: int, row_ids: Optional[np.ndarray] = None) -> np.ndarray:
        phase = self._phase_of.pop(msg_id, 0)
        return self._finish_get(self.wait(msg_id), row_ids, phase)

    def _prep_get_option(self, option: Optional[GetOption],
                         row_ids) -> Tuple[GetOption, int]:
        """Default option + pipelined plane selection: whole-table sparse
        Gets alternate between the worker's two staleness identities
        (worker_id, worker_id + num_workers — reference matrix.cpp:407-418)."""
        phase = 0
        if option is None:
            wid = self._channel.worker_id()
            if (self.is_sparse and self._pipelined and row_ids is None
                    and 0 <= wid < self._num_workers):
                phase = self._phase
                self._phase = 1 - self._phase
                wid += phase * self._num_workers
            option = GetOption(worker_id=wid)
        return option, phase

    def _finish_get(self, raw, row_ids, phase: int = 0) -> np.ndarray:
        if self.is_sparse and row_ids is None and isinstance(raw, np.ndarray):
            # admin-bypass reply (worker id out of range): dense whole table,
            # no staleness bookkeeping — do not touch the client cache
            self.rows_pulled += self.num_row
            return raw
        if self.is_sparse and row_ids is None:
            stale_ids, rows = raw
            cache = self._caches[phase]
            if len(stale_ids):
                cache[stale_ids] = rows
            self.rows_pulled += len(stale_ids)
            return np.array(cache, copy=True)
        if row_ids is None:
            self.rows_pulled += self.num_row
            return raw
        ids = np.asarray(row_ids).reshape(-1)
        self.rows_pulled += len(ids)
        if self.is_sparse:
            # the server marked these rows fresh for this worker (plane 0) —
            # mirror them into the plane-0 cache or a later whole-table
            # sparse get would serve stale values for exactly these rows
            self._caches[0][ids] = raw
        return raw

    # -- device IO (in-process workers only) --------------------------------
    # The LocalForward analog: a worker sharing the process with the table
    # exchanges DEVICE arrays with the dispatcher — candidate rows are
    # gathered in HBM and deltas scattered from HBM, no host copy on either
    # side. Remote proxies keep the host/wire path. Not available on
    # is_sparse tables (their client cache is host-resident).

    def get_device_async(self, row_ids: np.ndarray,
                         option: Optional[GetOption] = None) -> int:
        """Async candidate-row pull that stays in HBM. The reply (via
        ``wait_device``) is a ``(bucket, padded_cols)`` jax.Array whose
        slots ``>= len(row_ids)`` are sentinel copies — usable directly as
        a compact training space."""
        if self.is_sparse:
            log.fatal("device IO is not available on is_sparse tables")
        self._require_device_io()
        option, _ = self._prep_get_option(option, row_ids)
        return super().get_async((self._norm_ids(row_ids), option, True))

    def wait_device(self, msg_id: int, row_ids: np.ndarray) -> "jax.Array":
        raw = self.wait(msg_id)
        self._phase_of.pop(msg_id, None)
        self.rows_pulled += len(np.asarray(row_ids).reshape(-1))
        return raw

    def add_device_async(self, values: "jax.Array", row_ids: np.ndarray,
                         option: Optional[AddOption] = None) -> int:
        """Async device-resident add. ``values`` is a jax.Array of shape
        ``(len(row_ids), <=num_col)``; live ids unique, pad slots (if the
        caller pads) aim at ``num_row`` (the sentinel) with zero deltas."""
        if self.is_sparse:
            log.fatal("device IO is not available on is_sparse tables")
        self._require_device_io()
        option = self._default_add_option(option)
        return super().add_async(
            (np.asarray(row_ids, np.int32).reshape(-1), values, option))

    def transact_device_async(self, fn, others: Sequence["MatrixWorker"],
                              args: tuple = (),
                              touched: Optional[Sequence] = None) -> int:
        """Submit a fused multi-table device transaction (one dispatcher
        op, one device dispatch): ``fn(datas, states, *args) ->
        (new_datas, new_states, extra)`` over the device state of
        ``[this table, *others]``, with ``extra`` as the (device) reply.
        ``fn`` should be jitted with ``donate_argnums=(0, 1)`` — the
        tables' buffers are updated in place.

        ``fn`` may be a NAME registered via
        :func:`multiverso_tpu.runtime.programs.register_program` — the
        only form that works across a multihost mesh (a closure cannot
        ride a lockstep descriptor; a name resolves on every rank to the
        locally-built identical jit, and ``args`` must then be host data:
        numpy/scalars). Raw-callable form is in-process only.

        Plain async server only: round-gated/deferred servers
        (BSP/deterministic) account per-table clocks that a cross-table
        transaction cannot honor — callers check the server's
        ``gates_gets``/``defers_adds`` and use the staged pull/push path
        there."""
        if self.is_sparse:
            log.fatal("device IO is not available on is_sparse tables")
        named = isinstance(fn, str)
        multihost = Zoo.instance().multihost is not None
        if not named:
            self._require_device_io()  # closures are in-process-only
        server = Zoo.instance().server
        if not (getattr(server, "plain_async", False)
                or (named and getattr(server, "supports_named_transact",
                                      False))):
            log.fatal("transact_device_async requires the plain async "
                      "server (BSP/deterministic servers keep per-table "
                      "clocks a cross-table transaction cannot honor)")
        other_ids = []
        for o in others:
            st = getattr(o, "_server_table", None)
            if st is None:
                log.fatal("transact_device_async: %r is not an in-process "
                          "table", o)
            if getattr(o, "is_sparse", False) or getattr(st, "is_sparse",
                                                         False):
                # same guard as self: a transaction with touched=None
                # would silently skip staleness invalidation and serve
                # other workers stale rows from their client caches
                log.fatal("device IO is not available on is_sparse tables")
            other_ids.append((o.table_id, st))
        if named:
            if multihost:
                import jax
                for a in args:
                    if isinstance(a, jax.Array):
                        log.fatal("named transaction args must be host "
                                  "data under a multihost mesh (numpy/"
                                  "scalars) — device arrays cannot ride "
                                  "the lockstep control plane")
            # the named request carries table IDS, not live objects:
            # host-serializable, resolved rank-locally at execution
            return super().add_async(
                ("transact_named", fn, tuple(tid for tid, _ in other_ids),
                 tuple(args), touched))
        return super().add_async(("transact", fn,
                                  [st for _, st in other_ids],
                                  tuple(args), touched))

    @property
    def sentinel_row(self) -> int:
        return self._server_table.sentinel_row

    # -- add ---------------------------------------------------------------
    def _auto_sparse_rows(self, values, row_ids):
        """Worker-side nonzero-row auto-detect (reference matrix.cpp:148-182):
        a whole-table Add to a sparse table scans the delta and ships only
        the nonzero rows — the caller keeps the dense API."""
        if row_ids is not None or not self.is_sparse:
            return row_ids, values
        values = np.asarray(values, dtype=self.dtype).reshape(
            self.num_row, self.num_col)
        nz = np.nonzero(values.any(axis=1))[0].astype(np.int32)
        if len(nz) == self.num_row:
            return None, values
        return nz, values[nz]

    def add(self, values: np.ndarray, row_ids: Optional[np.ndarray] = None,
            option: Optional[AddOption] = None) -> None:
        row_ids, values = self._auto_sparse_rows(values, row_ids)
        option = self._default_add_option(option)
        super().add((self._norm_ids(row_ids), values, option))

    def add_async(self, values: np.ndarray, row_ids: Optional[np.ndarray] = None,
                  option: Optional[AddOption] = None) -> int:
        row_ids, values = self._auto_sparse_rows(values, row_ids)
        option = self._default_add_option(option)
        return super().add_async((self._norm_ids(row_ids), values, option))

    # -- helpers -----------------------------------------------------------
    def _norm_ids(self, row_ids) -> Optional[np.ndarray]:
        if row_ids is None:
            return None
        ids = np.asarray(row_ids, dtype=np.int32).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_row):
            log.fatal("Matrix row id out of range [0, %d)", self.num_row)
        return ids

    def _default_add_option(self, option: Optional[AddOption]) -> AddOption:
        if option is None:
            option = AddOption()
            option.worker_id = self._channel.worker_id()
        return option

    def _default_get_option(self, option: Optional[GetOption]) -> GetOption:
        if option is None:
            option = GetOption(worker_id=self._channel.worker_id())
        return option

    # -- TPU-era fast path -------------------------------------------------
    def get_device(self) -> jax.Array:
        return self._server_table.data
