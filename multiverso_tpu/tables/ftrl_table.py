"""FTRL table — proof of the table-extension API.

Reference capability (not copied): LogisticRegression defines custom
user-level tables — ``FTRLWorkerTable/FTRLServerTable`` with struct-valued
entries ``FTRLEntry{z, n}`` where the *server* runs the FTRL-proximal update
and Get materializes weights from (z, n)
(``Applications/LogisticRegression/src/util/ftrl_sparse_table.h:12-90``).

TPU-native re-design: (z, n) are two HBM-sharded arrays beside no weight
array at all — weights are *derived on device* inside the Get gather (the
FTRL closed form), so the server never stores stale w. Add ships raw
gradients; the whole update is one jitted donated call.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu import log
from multiverso_tpu.parallel import mesh as mesh_lib
from multiverso_tpu.runtime.zoo import Zoo
from multiverso_tpu.tables.base import ServerTable, WorkerTable


def ftrl_weights(z: jax.Array, n: jax.Array, alpha: float, beta: float,
                 lambda1: float, lambda2: float) -> jax.Array:
    """Closed-form FTRL-proximal weights from accumulator state."""
    shrunk = jnp.sign(z) * jnp.maximum(jnp.abs(z) - lambda1, 0.0)
    denom = (beta + jnp.sqrt(n)) / alpha + lambda2
    return -shrunk / denom


class FTRLServer(ServerTable):
    def __init__(self, size: int, alpha: float = 0.1, beta: float = 1.0,
                 lambda1: float = 1.0, lambda2: float = 1.0) -> None:
        super().__init__()
        zoo = Zoo.instance()
        self.size = int(size)
        self.alpha, self.beta = float(alpha), float(beta)
        self.lambda1, self.lambda2 = float(lambda1), float(lambda2)
        self.mesh = zoo.mesh
        self.padded = mesh_lib.pad_to_multiple(self.size, zoo.num_servers)
        sharding = mesh_lib.table_sharding(self.mesh, ndim=1)
        self.z = jax.device_put(np.zeros(self.padded, np.float32), sharding)
        self.n = jax.device_put(np.zeros(self.padded, np.float32), sharding)

        a, b, l1, l2 = self.alpha, self.beta, self.lambda1, self.lambda2

        def update(z, n, grad):
            w = ftrl_weights(z, n, a, b, l1, l2)
            sigma = (jnp.sqrt(n + grad * grad) - jnp.sqrt(n)) / a
            z = z + grad - sigma * w
            n = n + grad * grad
            return z, n

        self._update = jax.jit(update, donate_argnums=(0, 1))
        self._weights = jax.jit(
            lambda z, n: ftrl_weights(z, n, a, b, l1, l2))

    def process_add(self, request: Tuple[np.ndarray, Any]) -> None:
        grad, _option = request
        grad = np.asarray(grad, np.float32).reshape(-1)
        if grad.size != self.size:
            log.fatal("FTRLTable.add: grad size %d != %d", grad.size, self.size)
        if self.padded != self.size:
            grad = np.pad(grad, (0, self.padded - self.size))
        self.z, self.n = self._update(self.z, self.n, jnp.asarray(grad))

    def process_get(self, request: Any) -> np.ndarray:
        w = self._weights(self.z, self.n)
        return self._host_read(w)[: self.size]

    def store(self, stream) -> None:
        from multiverso_tpu.checkpoint import write_array
        write_array(stream, self._host_read(self.z)[: self.size])
        write_array(stream, self._host_read(self.n)[: self.size])

    def load(self, stream) -> None:
        from multiverso_tpu.checkpoint import read_array
        z = read_array(stream)
        n = read_array(stream)
        sharding = mesh_lib.table_sharding(self.mesh, ndim=1)
        pad = self.padded - self.size
        self.z = jax.device_put(np.pad(z.astype(np.float32), (0, pad)), sharding)
        self.n = jax.device_put(np.pad(n.astype(np.float32), (0, pad)), sharding)


class FTRLWorker(WorkerTable):
    """Client proxy: ``add`` ships raw gradients, ``get`` returns the derived
    FTRL weights."""

    def __init__(self, size: int, alpha: float = 0.1, beta: float = 1.0,
                 lambda1: float = 1.0, lambda2: float = 1.0,
                 server: Optional[FTRLServer] = None) -> None:
        super().__init__()
        self.size = int(size)
        self._server_table = server or FTRLServer(size, alpha, beta,
                                                  lambda1, lambda2)
        self._register(self._server_table)

    def get(self) -> np.ndarray:
        return super().get(None)

    def add(self, grad: np.ndarray) -> None:
        super().add((grad, None))

    def add_async(self, grad: np.ndarray) -> int:
        return super().add_async((grad, None))
