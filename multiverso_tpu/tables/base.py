"""Table layer contracts: WorkerTable (client proxy) and ServerTable (state).

Reference capability (not copied): ``WorkerTable`` client bookkeeping —
per-request waiter with expected-reply count, msg-id allocation, sync
wrappers ``Get/Add = Wait(XxxAsync(...))`` — and the abstract
``ServerTable::ProcessAdd/ProcessGet`` + ``Serializable::Store/Load``
checkpoint hooks (``include/multiverso/table_interface.h:24-75``,
``src/table.cpp``), with ``table_factory::CreateTable`` wiring the pair
(``include/multiverso/table_factory.h:16-26``).

TPU-native re-design: there is no Partition step on the client — sharding is
the server state's ``NamedSharding`` and XLA owns the partitioning. The async
handle (msg_id → Completion) and the sync-wrapper shape are preserved so
callers written against the reference's API port 1:1.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

from multiverso_tpu import log
from multiverso_tpu.dashboard import monitor
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id
from multiverso_tpu.runtime.zoo import Zoo
from multiverso_tpu.utils import Waiter


class Completion:
    """One outstanding request: a waiter plus its result slot."""

    __slots__ = ("_waiter", "result", "error")

    def __init__(self) -> None:
        self._waiter = Waiter(1)
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def done(self, result: Any) -> None:
        self.result = result
        self._waiter.notify()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self._waiter.notify()

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._waiter.wait(timeout):
            raise TimeoutError("table request timed out")
        if self.error is not None:
            raise self.error
        return self.result


class LocalChannel:
    """Default request channel: the in-process dispatcher queue (workers and
    server shards share the mesh — no wire). The remote equivalent lives in
    :mod:`multiverso_tpu.runtime.remote`."""

    def __init__(self) -> None:
        self._zoo = Zoo.instance()

    def worker_id(self) -> int:
        return self._zoo.current_worker_id()

    def submit(self, table_id: int, msg_type: MsgType, request: Any,
               msg_id: int, completion: "Completion") -> None:
        msg = Message(src=self.worker_id(), dst=-1, type=msg_type,
                      table_id=table_id, msg_id=msg_id,
                      data=[request, completion])
        self._zoo.server.send(msg)

    def post(self, table_id: int, msg_type: MsgType) -> None:
        """Fire-and-forget control message (Server_Finish_Train)."""
        msg = Message(src=self.worker_id(), dst=-1, type=msg_type,
                      table_id=table_id, msg_id=next_msg_id())
        self._zoo.server.send(msg)


class WorkerTable:
    """Client proxy: issues Get/Add messages, tracks outstanding replies."""

    def __init__(self, channel: Optional[Any] = None) -> None:
        self.table_id: int = -1
        self._channel = channel if channel is not None else LocalChannel()
        self._zoo = Zoo.instance() if channel is None else None
        self._pending: Dict[int, Completion] = {}
        self._pending_request: Dict[int, Any] = {}
        self._lock = threading.Lock()

    # -- wiring ------------------------------------------------------------
    def _register(self, server_table: "ServerTable") -> None:
        self.table_id = self._zoo.register_table(self, server_table)
        server_table.table_id = self.table_id

    # -- async machinery ---------------------------------------------------
    def _submit(self, msg_type: MsgType, request: Any) -> int:
        msg_id = next_msg_id()
        completion = Completion()
        with self._lock:
            self._pending[msg_id] = completion
            self._pending_request[msg_id] = request
        self._channel.submit(self.table_id, msg_type, request, msg_id,
                             completion)
        return msg_id

    def get_async(self, request: Any) -> int:
        return self._submit(MsgType.Request_Get, request)

    def add_async(self, request: Any) -> int:
        return self._submit(MsgType.Request_Add, request)

    def wait(self, msg_id: int) -> Any:
        with self._lock:
            completion = self._pending.pop(msg_id, None)
            request = self._pending_request.pop(msg_id, None)
        if completion is None:
            log.fatal("wait: unknown msg_id %d on table %d", msg_id, self.table_id)
        raw = completion.wait()
        if raw is None:
            return None
        return self.process_reply_get(raw, request)

    def process_reply_get(self, raw: Any, request: Any) -> Any:
        """Post-process a Get reply (reference: ``ProcessReplyGet`` writes
        into user buffers). Default: identity."""
        return raw

    def _require_device_io(self) -> None:
        """Guard for device-array-exchanging entry points: in-process
        proxies only — multihost lockstep descriptors and remote wire
        requests must be host-serializable."""
        if not getattr(self, "supports_device_io", False):
            log.fatal("device IO is in-process only (multihost/remote "
                      "proxies take the host paths)")

    # -- sync wrappers (Get/Add = Wait(Async)) ------------------------------
    # NOTE: these call _submit directly (not self.get_async) so subclasses can
    # override the async methods with their own signatures safely.
    def get(self, request: Any) -> Any:
        with monitor("WORKER_TABLE_SYNC_GET"):
            return self.wait(self._submit(MsgType.Request_Get, request))

    def add(self, request: Any) -> Any:
        with monitor("WORKER_TABLE_SYNC_ADD"):
            return self.wait(self._submit(MsgType.Request_Add, request))

    def query(self, vecs: Any, k: int, metric: str = "dot") -> Any:
        """Server-side top-k retrieval pushdown: score every row of the
        table against ``vecs`` ((n_q, dim) float32) under ``metric``
        (``dot`` | ``cosine``) and return ``(ids, scores)`` — each
        (n_q, k') with k' = min(k, rows), ranked score-descending with
        ties broken toward the lower global id. Slot-free on the server
        (never clocked, never WAL'd) and replica-servable, so results
        may trail the primary by the read tier's staleness budget.

        Bypasses wait()/process_reply_get: the reply is already the
        final (ids, scores) pair — per-kind Get post-processing (e.g.
        MatrixWorker's buffer fill) must not touch it."""
        from multiverso_tpu.query.engine import check_request
        request = check_request((vecs, k, metric))
        with monitor("WORKER_TABLE_SYNC_QUERY"):
            completion = Completion()
            self._channel.submit(self.table_id, MsgType.Request_Query,
                                 request, next_msg_id(), completion)
            return completion.wait()

    def finish_train(self) -> None:
        """Signal end-of-training so BSP clocks release peers
        (reference: ``Server_Finish_Train``)."""
        self._channel.post(self.table_id, MsgType.Server_Finish_Train)


class ServerTable:
    """Device-resident table shard set + checkpoint hooks."""

    def _unwrapped(self):
        """This server table with any lockstep wrapper peeled off (a
        named transaction's secondary tables are state holders, not
        dispatch points — the PRIMARY table's descriptor already covers
        the op; see MatrixServer._resolve_named). On any real table this
        is the identity; the multihost LockstepTable forwards it to its
        inner table via __getattr__."""
        return self

    def __init__(self) -> None:
        self.table_id: int = -1
        # Global position of this table's first row/element/key when it is
        # one shard of a range-partitioned table (shard/partition.py): the
        # member serves SHARD-LOCAL ids in [0, local size) — the router
        # translates — and advertises the offset in its remote directory
        # so clients and operators can see which span this member owns.
        # 0 = unsharded (or the first shard).
        self.row_offset: int = 0
        self._replicate = None  # lazy replicate-jit for multihost host reads
        # (scalars tuple, worker) -> device constants, LRU-bounded. A
        # repeated AddOption envelope (fixed-lr hot paths) hits the cache
        # and skips two host->device transfers per add; a churning
        # envelope (per-block lr decay) misses but cannot pin more than
        # _OPT_CACHE_MAX dead device buffers. Locked: the dispatcher
        # thread (process_add) and worker threads (the word2vec txn path)
        # both call _option_consts, and a concurrent move_to_end on a key
        # being popitem'd can raise KeyError.
        self._opt_cache: "OrderedDict" = OrderedDict()
        self._opt_cache_lock = threading.Lock()

    _OPT_CACHE_MAX = 256

    def _option_consts(self, option):
        """Device constants (worker index, scalars envelope) for an
        AddOption, cached so identical envelopes upload once. Requires
        ``self.num_workers``."""
        import jax.numpy as jnp
        key = (option.scalars(), int(option.worker_id))
        with self._opt_cache_lock:
            cached = self._opt_cache.get(key)
            if cached is not None:
                self._opt_cache.move_to_end(key)
                return cached
        # build device constants OUTSIDE the lock (host->device upload);
        # a racing duplicate insert is harmless — last writer wins
        scalars = jnp.asarray(option.scalars(), dtype=jnp.float32)
        worker = jnp.int32(max(option.worker_id, 0)
                           % max(1, self.num_workers))
        cached = (worker, scalars)
        with self._opt_cache_lock:
            self._opt_cache[key] = cached
            if len(self._opt_cache) > self._OPT_CACHE_MAX:
                self._opt_cache.popitem(last=False)
        return cached

    def remote_spec(self) -> Optional[Dict[str, Any]]:
        """Metadata a remote client needs to build a matching worker proxy
        (kind + shape + dtype); None = not servable over the wire."""
        return None

    def _host_read(self, arr) -> Any:
        """Device->host read of table state. Under a multi-process mesh the
        array is globally sharded and not fully addressable from one
        controller, so route through a replicating jit first (an XLA
        allgather — collective, which is safe here because every host-read
        site runs on the lockstep dispatcher/replay thread). Single-process
        meshes skip straight to ``device_get``."""
        import jax
        import numpy as np
        from multiverso_tpu.runtime.zoo import Zoo
        if Zoo.instance().multihost is not None:
            if self._replicate is None:
                from jax.sharding import NamedSharding, PartitionSpec
                self._replicate = jax.jit(
                    lambda x: x,
                    out_shardings=NamedSharding(self.mesh,
                                                PartitionSpec()))
            arr = self._replicate(arr)
        return np.asarray(jax.device_get(arr))

    def merge_add_requests(self, requests):
        """Fuse a PREFIX of a drained group of Add requests into ONE
        request (the dispatcher's micro-batch path, runtime/server.py):
        return ``(merged_request, rows, consumed)`` — where
        ``process_add(merged)`` is equivalent to applying the first
        ``consumed`` requests in turn (up to the commutative-Add
        reordering Downpour tolerates) and ``rows`` feeds the
        APPLY_BATCH_ROWS histogram — or None when even the first request
        cannot merge (the dispatcher then applies per message, exactly as
        before). Consuming a prefix lets a table bound the fused-apply
        size (e.g. the matrix row cap) without giving up batching for
        the remainder.

        Contract: MUST NOT mutate table state; the eventual
        ``process_add(merged)`` must validate before it mutates, so a
        raised error means nothing applied (the dispatcher retries the
        group per message). Default: no batching."""
        return None

    def process_add(self, request: Any) -> None:
        raise NotImplementedError

    def process_get(self, request: Any) -> Any:
        raise NotImplementedError

    # Serializable (checkpoint) hooks
    def store(self, stream) -> None:
        raise NotImplementedError

    def load(self, stream) -> None:
        raise NotImplementedError

    # -- live migration hooks (shard/reshard.py) ----------------------------
    # Raw-value slice transfer for key-range migration: extract hands the
    # coordinator the CURRENT values of a shard-local id range (no updater
    # involvement, mirrors store()); absorb installs values at a range on
    # the recipient, bypassing updaters entirely — a migrated value is
    # state, not a gradient. Only range-partitionable kinds implement
    # these; the migration planner refuses the rest before ever calling.
    def extract_range(self, lo: int, hi: int) -> Any:
        log.fatal("live migration is unsupported for %s (no extract_range)",
                  type(self).__name__)

    def absorb_range(self, start: int, values: Any) -> None:
        log.fatal("live migration is unsupported for %s (no absorb_range)",
                  type(self).__name__)
