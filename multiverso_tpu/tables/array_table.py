"""ArrayTable — 1-D dense distributed table.

Reference capability (not copied): contiguous range-sharded 1-D table across
servers, whole-table Get/Add only, server-side updater application
(``src/table/array_table.cpp``, ``include/multiverso/table/array_table.h``).

TPU-native re-design: the table is ONE ``jax.Array`` in HBM, sharded over the
``server`` mesh axis (padded to shard-divisible length); the reference's
client-side ``Partition`` (slicing the value blob per server rank) does not
exist — XLA partitions the donated jitted update. Optimizer state shards
live beside the data with identical layout. ``get_device()`` exposes the
sharded device array for zero-copy use inside jitted training steps — the
TPU-era fast path that host-RAM parameter servers could not offer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu import log
from multiverso_tpu.parallel import mesh as mesh_lib
from multiverso_tpu.runtime.zoo import Zoo
from multiverso_tpu.tables.base import ServerTable, WorkerTable
from multiverso_tpu.utils import async_upload
from multiverso_tpu.updaters import (AddOption, GetOption, SGDUpdater,
                                     Updater, get_updater)


def _make_whole_update(updater: Updater, jit: bool = True):
    """One whole-table update closed over the updater. Jitted+donated so
    the HBM buffers are reused in place; ``jit=False`` returns the raw
    traceable function for embedding in larger fused jits."""

    def f(data, states, delta, worker, scalars):
        if updater.per_worker_state:
            sliced = {k: jax.lax.dynamic_index_in_dim(v, worker, 0, keepdims=False)
                      for k, v in states.items()}
        else:
            sliced = {k: v[0] for k, v in states.items()}
        new_data, new_sliced = updater.apply(data, sliced, delta, scalars)
        if updater.per_worker_state:
            new_states = {k: jax.lax.dynamic_update_index_in_dim(states[k], new_sliced[k], worker, 0)
                          for k in states}
        else:
            new_states = {k: new_sliced[k][None] for k in states}
        return new_data, new_states

    return jax.jit(f, donate_argnums=(0, 1)) if jit else f


class ArrayServer(ServerTable):
    def __init__(self, size: int, dtype: Any = np.float32,
                 updater_type: str = "", num_workers: Optional[int] = None,
                 init_value: Optional[np.ndarray] = None) -> None:
        super().__init__()
        zoo = Zoo.instance()
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.mesh = zoo.mesh
        num_shards = zoo.num_servers
        self.num_workers = num_workers if num_workers is not None else zoo.num_workers
        self.padded = mesh_lib.pad_to_multiple(self.size, num_shards)
        sharding = mesh_lib.table_sharding(self.mesh, ndim=1)

        init = np.zeros(self.padded, dtype=self.dtype)
        if init_value is not None:
            init[: self.size] = np.asarray(init_value, dtype=self.dtype)
        self.data = jax.device_put(init, sharding)

        self.updater = get_updater(self.dtype, updater_type)
        worker_dim = self.num_workers if self.updater.per_worker_state else 1
        self.states: Dict[str, jax.Array] = {}
        for name, (shape_suffix, sdtype) in self.updater.state_spec(
                (self.padded,), self.dtype).items():
            s_shard = mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=1)
            self.states[name] = jax.device_put(
                np.zeros((worker_dim,) + tuple(shape_suffix), dtype=sdtype), s_shard)

        self._update = _make_whole_update(self.updater)
        self._codecs: Dict = {}  # leaf-signature -> (to_flat, from_flat)

    # -- server ops --------------------------------------------------------
    def merge_add_requests(self, requests):
        """Whole-array host deltas sum into ONE update — linear updaters
        only (a stateful updater applied once to a summed delta is a
        different operator than N sequential applies). The fused
        add+get form (3-tuple), leaf-tagged forms, and device-resident
        deltas all refuse: their replies/payloads are per-request."""
        if type(self.updater) not in (Updater, SGDUpdater):
            return None
        total = None
        consumed = 0
        for request in requests:
            if not (isinstance(request, tuple) and len(request) == 2):
                break
            delta, _option = request
            if delta is None or isinstance(delta, jax.Array):
                break
            arr = np.asarray(delta, dtype=self.dtype).reshape(-1)
            if arr.size != self.size:
                break  # per-message path reports the real error
            total = arr.astype(self.dtype, copy=True) if total is None \
                else total + arr
            consumed += 1
        if total is None:
            return None
        return (total, requests[0][1]), int(total.size), consumed

    def _leaf_codec(self, leaves):
        """jitted (to_flat, from_flat) for a list-of-arrays signature.
        from_flat's outputs are committed to ONE device (out_shardings):
        worker threads then compute on single-device arrays only, so every
        cross-shard collective stays on the dispatcher thread — concurrent
        sharded executions from N worker threads deadlock the CPU
        backend's rendezvous (and serialize badly on real meshes)."""
        key = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        codec = self._codecs.get(key)
        if codec is not None:
            return codec
        shapes = [tuple(l.shape) for l in leaves]
        dtypes = [l.dtype for l in leaves]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        if sum(sizes) != self.size:
            log.fatal("leaf signature totals %d, table size %d",
                      sum(sizes), self.size)
        pad, dtype = self.padded - self.size, self.dtype

        def to_flat_impl(ls):
            flat = (jnp.concatenate(
                [jnp.ravel(x).astype(dtype) for x in ls])
                if ls else jnp.zeros(0, dtype))
            return jnp.pad(flat, (0, pad)) if pad else flat

        to_flat = jax.jit(to_flat_impl)

        from jax.sharding import SingleDeviceSharding
        dev = SingleDeviceSharding(jax.devices()[0])
        # on a 1-device mesh (the common real-TPU case) sharded == single
        # device, so both boundary transfers are pure overhead (~1 tunnel
        # dispatch per leaf) — skip them
        multi = self.mesh is not None and self.mesh.size > 1

        def split_impl(flat):
            out, n = [], 0
            for shape, dt, size in zip(shapes, dtypes, sizes):
                out.append(flat[n:n + size].reshape(shape).astype(dt))
                n += size
            return out

        split = jax.jit(split_impl)

        def from_flat(flat):
            # split stays sharded in-jit (jit rejects mixed device sets in
            # out_shardings); the gather to ONE device is an explicit
            # transfer issued here, on the dispatcher thread
            leaves = split(flat)
            return jax.device_put(leaves, dev) if multi else leaves

        fused = fused_sync = None
        if not multi:
            # single-device mesh: the whole sync — flatten, update,
            # access, split — is ONE compiled dispatch (mixed device sets
            # block this on sharded meshes, which use the staged path)
            update_raw = _make_whole_update(self.updater, jit=False)
            access = self.updater.access

            def fused_impl(data, states, ls, worker, scalars):
                data, states = update_raw(data, states, to_flat_impl(ls),
                                          worker, scalars)
                return data, states, split_impl(access(data))

            fused = jax.jit(fused_impl, donate_argnums=(0, 1))

            def fused_sync_impl(data, states, new_ls, last_ls, worker,
                                scalars):
                # delta computed HERE (not in a worker-thread jit): on a
                # tunneled TPU each dispatch submission costs ~2.5-4 ms,
                # so the whole ASGD sync — delta, update, access, split,
                # baseline copy — must be ONE dispatch (measured: 3
                # dispatches = 9.1 ms/sync vs a ~3 ms floor)
                delta = to_flat_impl(new_ls) - to_flat_impl(last_ls)
                data, states = update_raw(data, states, delta, worker,
                                          scalars)
                merged = split_impl(access(data))
                # the baseline is a DISTINCT buffer set: callers donate the
                # merged leaves into their train step, which would delete a
                # shared baseline out from under the next delta
                baseline = [jnp.copy(x) for x in merged]
                return data, states, merged, baseline

            # donate last_ls too (argnum 3): the view owns those buffers
            # exclusively and replaces them with `baseline` on return
            fused_sync = jax.jit(fused_sync_impl, donate_argnums=(0, 1, 3))

            def fused_push_impl(data, states, new_ls, last_ls, worker,
                                scalars):
                # reply-free pair push for round-gated/deferred servers:
                # no merged split, no baseline copy — the client pulls
                # through a properly gated Get instead
                delta = to_flat_impl(new_ls) - to_flat_impl(last_ls)
                return update_raw(data, states, delta, worker, scalars)

            fused_push = jax.jit(fused_push_impl, donate_argnums=(0, 1, 3))
        else:
            fused_push = None

        def pair_delta_impl(new_ls, last_ls):
            return to_flat_impl(new_ls) - to_flat_impl(last_ls)

        pair_delta = jax.jit(pair_delta_impl)
        # distinct-buffer device-local copy (staged multi-device path):
        # far cheaper than a second split + cross-device gather
        copy_leaves = jax.jit(lambda ls: [jnp.copy(x) for x in ls])

        codec = (to_flat, from_flat, fused, fused_sync, pair_delta,
                 fused_push, copy_leaves)
        self._codecs[key] = codec
        return codec

    def process_add(self, request) -> Optional[list]:
        want_get = False
        kind = request[0] if isinstance(request[0], str) else None
        if kind == "leaves_sync":
            # one-dispatch whole-model sync: (new, last) leaf lists in,
            # (merged, baseline) out — see fused_sync_impl in _leaf_codec
            _, new_ls, last_ls, option = request
            option = option or AddOption()
            (_, from_flat, _, fused_sync, pair_delta, _,
             copy_leaves) = self._leaf_codec(list(new_ls))
            worker, scalars = self._option_consts(option)
            if fused_sync is not None:  # single-device: one dispatch
                self.data, self.states, merged, baseline = fused_sync(
                    self.data, self.states, list(new_ls), list(last_ls),
                    worker, scalars)
                return (merged, baseline)
            # staged multi-device path: jitted pair-delta, scatter to the
            # table sharding, one from_flat gather, then a device-local
            # copy for the distinct baseline buffer set
            delta = jax.device_put(
                pair_delta(list(new_ls), list(last_ls)),
                mesh_lib.table_sharding(self.mesh, ndim=1))
            self.data, self.states = self._update(self.data, self.states,
                                                  delta, worker, scalars)
            merged = from_flat(self.updater.access(self.data))
            return (merged, copy_leaves(merged))
        if kind == "leaves_push":
            # reply-free pair push (round-gated/deferred servers): apply
            # new-last, materialize nothing — the client follows with a
            # properly gated Get
            _, new_ls, last_ls, option = request
            option = option or AddOption()
            _, _, _, _, pair_delta, fused_push, _ = self._leaf_codec(
                list(new_ls))
            worker, scalars = self._option_consts(option)
            if fused_push is not None:  # single-device: one dispatch
                self.data, self.states = fused_push(
                    self.data, self.states, list(new_ls), list(last_ls),
                    worker, scalars)
                return None
            delta = jax.device_put(
                pair_delta(list(new_ls), list(last_ls)),
                mesh_lib.table_sharding(self.mesh, ndim=1))
            self.data, self.states = self._update(self.data, self.states,
                                                  delta, worker, scalars)
            return None
        if kind == "leaves":
            # fused whole-model sync: delta arrives as the caller's leaf
            # list, the merged value returns the same way — one hop, all
            # sharded math right here on the dispatcher thread
            _, leaves, option = request
            option = option or AddOption()
            to_flat, from_flat, fused, _, _, _, _ = self._leaf_codec(leaves)
            worker, scalars = self._option_consts(option)
            if fused is not None:  # single-device: one compiled dispatch
                self.data, self.states, out = fused(
                    self.data, self.states, list(leaves), worker, scalars)
                return out
            # staged multi-device path: explicit scatter to the table
            # sharding (the jitted update can't take mixed device sets)
            delta = jax.device_put(
                to_flat(list(leaves)),
                mesh_lib.table_sharding(self.mesh, ndim=1))
            self.data, self.states = self._update(self.data, self.states,
                                                  delta, worker, scalars)
            return from_flat(self.updater.access(self.data))
        if len(request) == 3:  # fused add+get (flat device sync path)
            delta, option, want_get = request
        else:
            delta, option = request
        option = option or AddOption()
        # host deltas are normalized to device arrays up front; a
        # jax.Array input never touches the host (the TPU-era ASGD path —
        # param sync is HBM-to-HBM)
        if not isinstance(delta, jax.Array):
            host = np.asarray(delta, dtype=self.dtype)
            if host is delta:
                # asarray was a no-op, so the enqueued upload would read
                # the CALLER's buffer — which it may mutate the moment
                # add_async returns. Snapshot it before going async.
                host = host.copy()
            delta = async_upload(host)
        delta = delta.reshape(-1).astype(self.dtype)
        if delta.size != self.size:
            log.fatal("ArrayTable.add: delta size %d != table size %d",
                      delta.size, self.size)
        if self.padded != self.size:
            delta = jnp.pad(delta, (0, self.padded - self.size))
        # administrative access (worker id -1) charges slot 0, not slot n-1
        worker, scalars = self._option_consts(option)
        self.data, self.states = self._update(self.data, self.states,
                                              delta, worker, scalars)
        if want_get:
            # fused reply: the post-add global value, still in HBM — one
            # dispatcher hop for the whole ASGD sync instead of two
            return self._device_value()
        return None

    def _device_value(self) -> jax.Array:
        out = self.updater.access(self.data)[: self.size]
        # jnp.copy: with an identity access and size == padded the slice
        # can alias self.data, whose buffer the NEXT add donates — the
        # caller's reply would be deleted out from under it
        return jnp.copy(out)

    def process_get(self, request) -> np.ndarray:
        device_out = False
        if isinstance(request, tuple):
            if isinstance(request[0], str) and request[0] == "leaves":
                # leaf-shaped device get: reply mirrors the template's
                # shapes/dtypes, committed single-device (see _leaf_codec)
                _, template, _option = request
                _, from_flat, _, _, _, _, _ = self._leaf_codec(template)
                return from_flat(self.updater.access(self.data))
            request, device_out = request  # in-process device-out form
        if device_out:
            return self._device_value()  # stays in HBM, donation-safe
        out = self.updater.access(self.data)
        return self._host_read(out)[: self.size]

    def remote_spec(self):
        return {"kind": "array", "size": self.size, "dtype": self.dtype.str}

    # -- checkpoint --------------------------------------------------------
    def store(self, stream) -> None:
        from multiverso_tpu.checkpoint import write_array, write_state_dict
        write_array(stream, self._host_read(self.data)[: self.size])
        write_state_dict(stream, {
            name: self._host_read(arr)[:, : self.size]
            for name, arr in self.states.items()})

    def load(self, stream) -> None:
        from multiverso_tpu.checkpoint import read_array, read_state_dict
        arr = read_array(stream)
        if arr.size != self.size:
            log.fatal("ArrayTable.load: size mismatch %d != %d", arr.size, self.size)
        padded = np.zeros(self.padded, dtype=self.dtype)
        padded[: self.size] = arr.astype(self.dtype)
        self.data = jax.device_put(padded, mesh_lib.table_sharding(self.mesh, ndim=1))
        loaded = read_state_dict(stream)
        s_shard = mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=1)
        for name, cur in self.states.items():
            got = loaded.get(name)
            if got is None:
                continue  # v1 checkpoint: that state resets (pre-v2 behavior)
            if got.shape[0] != cur.shape[0]:
                # per-worker state from a world with a different worker
                # count: elastic restarts keep working — reset like v1
                log.info("checkpoint: %s worker dim %d != %d; resetting "
                         "that updater state", name, got.shape[0],
                         cur.shape[0])
                continue
            full = np.zeros(cur.shape, np.dtype(cur.dtype))
            full[:, : self.size] = got
            self.states[name] = jax.device_put(full, s_shard)

    # -- live migration (shard/reshard.py) ---------------------------------
    def extract_range(self, lo: int, hi: int):
        """Raw values of shard-local elements [lo, hi) — the migration
        transfer unit (updater state excluded; documented reset)."""
        return self._host_read(self.data)[lo:hi]

    def absorb_range(self, start: int, values) -> None:
        """Install raw values at [start, start+len), bypassing updaters —
        the recipient side of extract_range."""
        values = np.asarray(values, dtype=self.dtype).reshape(-1)
        n = values.size
        if start < 0 or start + n > self.size:
            log.fatal("absorb_range [%d, %d) outside [0, %d)",
                      start, start + n, self.size)
        padded = np.array(self._host_read(self.data))
        padded[start:start + n] = values
        self.data = jax.device_put(
            padded, mesh_lib.table_sharding(self.mesh, ndim=1))


class ArrayWorker(WorkerTable):
    """Client proxy for a 1-D dense table (whole-table Get/Add)."""

    def __init__(self, size: int, dtype: Any = np.float32,
                 updater_type: str = "",
                 init_value: Optional[np.ndarray] = None,
                 server: Optional[ArrayServer] = None) -> None:
        super().__init__()
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self._server_table = server or ArrayServer(
            size, dtype, updater_type, init_value=init_value)
        self._register(self._server_table)
        if Zoo.instance().multihost is not None:
            # device IO exchanges jax.Arrays with the dispatcher; lockstep
            # descriptors must be host-serializable — host paths only
            self.supports_device_io = False

    # -- API (mirrors reference ArrayWorker + python binding handler) -------
    def get(self, option: Optional[GetOption] = None) -> np.ndarray:
        return super().get(option)

    def get_async(self, option: Optional[GetOption] = None) -> int:
        return super().get_async(option)

    def add(self, delta: np.ndarray, option: Optional[AddOption] = None) -> None:
        option = self._default_option(option)
        super().add((delta, option))

    def add_async(self, delta: np.ndarray, option: Optional[AddOption] = None) -> int:
        option = self._default_option(option)
        return super().add_async((delta, option))

    def _default_option(self, option: Optional[AddOption]) -> AddOption:
        if option is None:
            option = AddOption()
            option.worker_id = self._channel.worker_id()
        return option

    # -- TPU-era fast path -------------------------------------------------
    supports_device_io = True

    def get_device(self) -> jax.Array:
        """The live sharded device array (valid until the next add)."""
        return self._server_table.data

    def get_device_async(self, option: Optional[GetOption] = None) -> int:
        """Dispatcher-ordered Get whose reply STAYS in HBM: a (size,)
        jax.Array reflecting every add queued before it. Unlike
        :meth:`get_device` this is safe against concurrent adds."""
        self._require_device_io()
        return super().get_async((option, True))

    def add_device_async(self, delta: "jax.Array",
                         option: Optional[AddOption] = None) -> int:
        """Async add of a DEVICE-resident (size,) delta — no host copy;
        the dispatcher applies it via the same jitted updater."""
        self._require_device_io()
        option = self._default_option(option)
        return super().add_async((delta, option))

    def sync_device_async(self, delta: "jax.Array",
                          option: Optional[AddOption] = None) -> int:
        """Fused device add+get: ONE dispatcher hop whose reply is the
        post-add global value in HBM. Deferred-apply servers (BSP /
        deterministic) reply None — callers fall back to an explicit
        get_device_async."""
        self._require_device_io()
        option = self._default_option(option)
        return super().add_async((delta, option, True))

    def sync_leaves_async(self, delta_leaves: list,
                          option: Optional[AddOption] = None,
                          last_leaves: Optional[list] = None) -> int:
        """Fused whole-model sync in the caller's own leaf shapes: ONE
        dispatcher hop; the reply is the merged value as a list of
        SINGLE-DEVICE arrays (safe for concurrent worker-thread compute —
        see ``ArrayServer._leaf_codec``). The leaf sizes must total the
        table size. Deferred-apply servers reply None; fall back to
        ``get_leaves_async``.

        With ``last_leaves``, ``delta_leaves`` is instead the NEW value and
        the server computes ``new - last`` in the same dispatch, replying
        ``(merged, baseline)`` where ``baseline`` is a distinct buffer set
        the caller may keep while donating ``merged``. ``last_leaves`` is
        donated — the caller must own those buffers exclusively."""
        self._require_device_io()
        option = self._default_option(option)
        if last_leaves is not None:
            return super().add_async(("leaves_sync", list(delta_leaves),
                                      list(last_leaves), option))
        return super().add_async(("leaves", list(delta_leaves), option))

    def push_leaves_async(self, new_leaves: list, last_leaves: list,
                          option: Optional[AddOption] = None) -> int:
        """Reply-free pair push: the server applies ``new - last`` and
        materializes nothing. For round-gated/deferred servers, where a
        fused merged reply would be discarded anyway — follow with a
        (gated) ``get_leaves_async``. ``last_leaves`` is donated."""
        self._require_device_io()
        option = self._default_option(option)
        return super().add_async(("leaves_push", list(new_leaves),
                                  list(last_leaves), option))

    def get_leaves_async(self, template_leaves: list,
                         option: Optional[GetOption] = None) -> int:
        """Device get shaped like ``template_leaves`` (values unused, only
        shapes/dtypes), single-device committed."""
        self._require_device_io()
        return super().get_async(("leaves", list(template_leaves), option))
