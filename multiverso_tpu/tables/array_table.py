"""ArrayTable — 1-D dense distributed table.

Reference capability (not copied): contiguous range-sharded 1-D table across
servers, whole-table Get/Add only, server-side updater application
(``src/table/array_table.cpp``, ``include/multiverso/table/array_table.h``).

TPU-native re-design: the table is ONE ``jax.Array`` in HBM, sharded over the
``server`` mesh axis (padded to shard-divisible length); the reference's
client-side ``Partition`` (slicing the value blob per server rank) does not
exist — XLA partitions the donated jitted update. Optimizer state shards
live beside the data with identical layout. ``get_device()`` exposes the
sharded device array for zero-copy use inside jitted training steps — the
TPU-era fast path that host-RAM parameter servers could not offer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu import log
from multiverso_tpu.parallel import mesh as mesh_lib
from multiverso_tpu.runtime.zoo import Zoo
from multiverso_tpu.tables.base import ServerTable, WorkerTable
from multiverso_tpu.updaters import AddOption, GetOption, Updater, get_updater


def _make_whole_update(updater: Updater):
    """Jit one whole-table update closed over the updater. Donated so the
    HBM buffers are reused in place."""

    def f(data, states, delta, worker, scalars):
        if updater.per_worker_state:
            sliced = {k: jax.lax.dynamic_index_in_dim(v, worker, 0, keepdims=False)
                      for k, v in states.items()}
        else:
            sliced = {k: v[0] for k, v in states.items()}
        new_data, new_sliced = updater.apply(data, sliced, delta, scalars)
        if updater.per_worker_state:
            new_states = {k: jax.lax.dynamic_update_index_in_dim(states[k], new_sliced[k], worker, 0)
                          for k in states}
        else:
            new_states = {k: new_sliced[k][None] for k in states}
        return new_data, new_states

    return jax.jit(f, donate_argnums=(0, 1))


class ArrayServer(ServerTable):
    def __init__(self, size: int, dtype: Any = np.float32,
                 updater_type: str = "", num_workers: Optional[int] = None,
                 init_value: Optional[np.ndarray] = None) -> None:
        super().__init__()
        zoo = Zoo.instance()
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.mesh = zoo.mesh
        num_shards = zoo.num_servers
        self.num_workers = num_workers if num_workers is not None else zoo.num_workers
        self.padded = mesh_lib.pad_to_multiple(self.size, num_shards)
        sharding = mesh_lib.table_sharding(self.mesh, ndim=1)

        init = np.zeros(self.padded, dtype=self.dtype)
        if init_value is not None:
            init[: self.size] = np.asarray(init_value, dtype=self.dtype)
        self.data = jax.device_put(init, sharding)

        self.updater = get_updater(self.dtype, updater_type)
        worker_dim = self.num_workers if self.updater.per_worker_state else 1
        self.states: Dict[str, jax.Array] = {}
        for name, (shape_suffix, sdtype) in self.updater.state_spec(
                (self.padded,), self.dtype).items():
            s_shard = mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=1)
            self.states[name] = jax.device_put(
                np.zeros((worker_dim,) + tuple(shape_suffix), dtype=sdtype), s_shard)

        self._update = _make_whole_update(self.updater)

    # -- server ops --------------------------------------------------------
    def process_add(self, request: Tuple[np.ndarray, Optional[AddOption]]) -> None:
        delta, option = request
        option = option or AddOption()
        delta = np.asarray(delta, dtype=self.dtype).reshape(-1)
        if delta.size != self.size:
            log.fatal("ArrayTable.add: delta size %d != table size %d",
                      delta.size, self.size)
        if self.padded != self.size:
            delta = np.pad(delta, (0, self.padded - self.size))
        scalars = jnp.asarray(option.scalars(), dtype=jnp.float32)
        # administrative access (worker id -1) charges slot 0, not slot n-1
        worker = jnp.int32(max(option.worker_id, 0) % max(1, self.num_workers))
        self.data, self.states = self._update(self.data, self.states,
                                              jnp.asarray(delta), worker, scalars)

    def process_get(self, request: Optional[GetOption]) -> np.ndarray:
        out = self.updater.access(self.data)
        return np.asarray(jax.device_get(out))[: self.size]

    def remote_spec(self):
        return {"kind": "array", "size": self.size, "dtype": self.dtype.str}

    # -- checkpoint --------------------------------------------------------
    def store(self, stream) -> None:
        from multiverso_tpu.checkpoint import write_array
        write_array(stream, np.asarray(jax.device_get(self.data))[: self.size])

    def load(self, stream) -> None:
        from multiverso_tpu.checkpoint import read_array
        arr = read_array(stream)
        if arr.size != self.size:
            log.fatal("ArrayTable.load: size mismatch %d != %d", arr.size, self.size)
        padded = np.zeros(self.padded, dtype=self.dtype)
        padded[: self.size] = arr.astype(self.dtype)
        self.data = jax.device_put(padded, mesh_lib.table_sharding(self.mesh, ndim=1))


class ArrayWorker(WorkerTable):
    """Client proxy for a 1-D dense table (whole-table Get/Add)."""

    def __init__(self, size: int, dtype: Any = np.float32,
                 updater_type: str = "",
                 init_value: Optional[np.ndarray] = None,
                 server: Optional[ArrayServer] = None) -> None:
        super().__init__()
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self._server_table = server or ArrayServer(
            size, dtype, updater_type, init_value=init_value)
        self._register(self._server_table)

    # -- API (mirrors reference ArrayWorker + python binding handler) -------
    def get(self, option: Optional[GetOption] = None) -> np.ndarray:
        return super().get(option)

    def get_async(self, option: Optional[GetOption] = None) -> int:
        return super().get_async(option)

    def add(self, delta: np.ndarray, option: Optional[AddOption] = None) -> None:
        option = self._default_option(option)
        super().add((delta, option))

    def add_async(self, delta: np.ndarray, option: Optional[AddOption] = None) -> int:
        option = self._default_option(option)
        return super().add_async((delta, option))

    def _default_option(self, option: Optional[AddOption]) -> AddOption:
        if option is None:
            option = AddOption()
            option.worker_id = self._channel.worker_id()
        return option

    # -- TPU-era fast path -------------------------------------------------
    def get_device(self) -> jax.Array:
        """The live sharded device array (valid until the next add)."""
        return self._server_table.data
