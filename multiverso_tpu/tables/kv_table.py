"""KVTable — distributed key-value table with arbitrary integer keys.

Reference capability (not copied): header-only distributed
``unordered_map<Key,Val>`` hash-sharded ``key % num_servers``, with a
worker-side local cache ``raw()`` (``include/multiverso/table/kv_table.h``);
its ``Store/Load`` were Fatal stubs — implemented for real here.

TPU-native design note: in the reference this table holds *control-plane*
state (e.g. word counts) on host RAM. The rebuild keeps that contract —
host-side store behind the dispatcher thread (so the consistency modes apply
uniformly) — while the *data-plane* sparse use case (huge embedding /
topic-count matrices keyed by token id) belongs to the row-sharded
MatrixTable / embedding ops, which keep values in HBM. A device-resident
static-capacity hash table is tracked as a follow-up in ops/.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterable, Optional, Tuple, Union

import numpy as np

from multiverso_tpu.tables.base import ServerTable, WorkerTable


class KVServer(ServerTable):
    def __init__(self, value_dtype: Any = np.float32) -> None:
        super().__init__()
        self.value_dtype = np.dtype(value_dtype)
        self._store: Dict[int, Any] = {}

    def process_add(self, request) -> None:
        keys, values, _option = request
        for k, v in zip(keys, values):
            if k in self._store:
                self._store[k] = self._store[k] + v
            else:
                self._store[k] = v

    def process_get(self, request):
        keys, _option = request
        if keys is None:
            return dict(self._store)
        zero = self.value_dtype.type(0)
        return [self._store.get(k, zero) for k in keys]

    def remote_spec(self):
        return {"kind": "kv", "dtype": self.value_dtype.str}

    def store(self, stream) -> None:
        items = sorted(self._store.items())
        stream.write(struct.pack("<q", len(items)))
        for k, v in items:
            arr = np.asarray(v, dtype=self.value_dtype)
            stream.write(struct.pack("<q", int(k)))
            stream.write(arr.tobytes() or self.value_dtype.type(0).tobytes())

    def load(self, stream) -> None:
        (count,) = struct.unpack("<q", stream.read(8))
        self._store.clear()
        item = self.value_dtype.itemsize
        for _ in range(count):
            (k,) = struct.unpack("<q", stream.read(8))
            v = np.frombuffer(stream.read(item), dtype=self.value_dtype)[0]
            self._store[k] = v


class KVWorker(WorkerTable):
    """Client proxy with a local cache (reference: ``raw()``)."""

    def __init__(self, value_dtype: Any = np.float32,
                 server: Optional[KVServer] = None) -> None:
        super().__init__()
        self.value_dtype = np.dtype(value_dtype)
        self._server_table = server or KVServer(value_dtype)
        self._register(self._server_table)
        self._raw: Dict[int, Any] = {}

    def raw(self) -> Dict[int, Any]:
        return self._raw

    def get(self, keys: Union[int, Iterable[int], None] = None):
        single = isinstance(keys, (int, np.integer))
        norm = None if keys is None else ([int(keys)] if single else [int(k) for k in keys])
        result = super().get((norm, None))
        if norm is None:
            self._raw = result
            return dict(result)
        for k, v in zip(norm, result):
            self._raw[k] = v
        return result[0] if single else result

    def add(self, keys: Union[int, Iterable[int]], values) -> None:
        norm, vals = self._normalize(keys, values)
        super().add((norm, vals, None))

    def add_async(self, keys, values) -> int:
        norm, vals = self._normalize(keys, values)
        return super().add_async((norm, vals, None))

    def _normalize(self, keys, values) -> Tuple[list, list]:
        if isinstance(keys, (int, np.integer)):
            return [int(keys)], [self.value_dtype.type(values)]
        norm = [int(k) for k in keys]
        vals = [self.value_dtype.type(v) for v in values]
        return norm, vals
