"""KVTable — distributed key-value table with integer keys.

Reference capability (not copied): header-only distributed
``unordered_map<Key,Val>`` hash-sharded ``key % num_servers``, with a
worker-side local cache ``raw()`` (``include/multiverso/table/kv_table.h``);
its ``Store/Load`` were Fatal stubs — implemented for real here.

Two server backends behind one worker API:

* :class:`KVServer` — host dict behind the dispatcher. Control-plane use
  (word counts, arbitrary-width python ints).
* :class:`DeviceKVServer` (``capacity=N``) — the data-plane design: keys
  are placed on a server shard by ``key % num_servers`` (the reference's
  placement contract, observable in the per-shard key arrays) and each
  shard holds a static-capacity open-addressing hash in HBM
  (:mod:`multiverso_tpu.ops.device_hash`), with Get/Add as one jitted
  ``shard_map`` program over the table mesh — the lightLDA-shaped
  sparse-KV store (SURVEY §7 hard part (e)).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterable, Optional, Tuple, Union

import numpy as np

from multiverso_tpu import log
from multiverso_tpu.tables.base import ServerTable, WorkerTable
from multiverso_tpu.utils import next_pow2


class KVServer(ServerTable):
    def __init__(self, value_dtype: Any = np.float32) -> None:
        super().__init__()
        self.value_dtype = np.dtype(value_dtype)
        self._store: Dict[int, Any] = {}

    def merge_add_requests(self, requests):
        """Key/value add streams concatenate: ``process_add`` folds the
        merged pair list in exactly the original arrival order, so one
        fused apply is bit-identical to per-message applies — the only
        saving is the per-message dispatch/WAL-bracket overhead."""
        keys: list = []
        values: list = []
        consumed = 0
        for request in requests:
            if not (isinstance(request, tuple) and len(request) == 3):
                break
            k, v, _option = request
            if k is None or v is None or len(k) != len(v):
                break  # per-message path reports the real error
            keys.extend(list(k))
            values.extend(list(v))
            consumed += 1
        if not consumed:
            return None
        return (keys, values, requests[0][2]), len(keys), consumed

    def process_add(self, request) -> None:
        keys, values, _option = request
        for k, v in zip(keys, values):
            if k in self._store:
                self._store[k] = self._store[k] + v
            else:
                self._store[k] = v

    def process_get(self, request):
        keys, _option = request
        if keys is None:
            return dict(self._store)
        zero = self.value_dtype.type(0)
        return [self._store.get(k, zero) for k in keys]

    def remote_spec(self):
        return {"kind": "kv", "dtype": self.value_dtype.str}

    def store(self, stream) -> None:
        items = sorted(self._store.items())
        stream.write(struct.pack("<q", len(items)))
        for k, v in items:
            arr = np.asarray(v, dtype=self.value_dtype)
            stream.write(struct.pack("<q", int(k)))
            stream.write(arr.tobytes() or self.value_dtype.type(0).tobytes())

    def load(self, stream) -> None:
        (count,) = struct.unpack("<q", stream.read(8))
        self._store.clear()
        item = self.value_dtype.itemsize
        for _ in range(count):
            (k,) = struct.unpack("<q", stream.read(8))
            v = np.frombuffer(stream.read(item), dtype=self.value_dtype)[0]
            self._store[k] = v


class TieredKVServer(KVServer):
    """KVServer whose value store is hot/cold tiered (multiverso_tpu/
    store/, docs/tiered_storage.md). Scalars ride the tier as width-1
    rows; numeric dtypes only (the host KVServer also stores python
    objects — those cannot spill to fixed-width segments).

    ``remote_spec`` still reports ``kind=kv``, so remote proxies and
    every durability/replication layer treat it as a plain KV table."""

    def __init__(self, value_dtype: Any = np.float32,
                 resident_bytes: Optional[int] = None,
                 cold_bits: Optional[int] = None,
                 tier_dir: Optional[str] = None,
                 admit_touches: Optional[int] = None) -> None:
        super().__init__(value_dtype)
        if self.value_dtype.kind not in "fiu":
            log.fatal("tiered KV values must be numeric (got %s); the "
                      "in-RAM KV table handles object values",
                      self.value_dtype)
        from multiverso_tpu.store import TieredStore
        self._tier = TieredStore(1, self.value_dtype,
                                 resident_bytes=resident_bytes,
                                 cold_bits=cold_bits, directory=tier_dir,
                                 admit_touches=admit_touches)
        self._store = None  # any missed base-class path must fail loudly

    def process_add(self, request) -> None:
        keys, values, _option = request
        tier = self._tier
        dtype = self.value_dtype
        for k, v in zip(keys, values):
            k = int(k)
            row = tier.get_for_update(k)
            if row is None:
                tier.put(k, np.array([v], dtype=dtype))
            else:
                row[0] = row[0] + dtype.type(v)
        tier.maybe_maintain()

    def process_get(self, request):
        keys, _option = request
        if keys is None:
            return {int(k): self.value_dtype.type(row[0])
                    for k, row in self._tier.items()}
        zero = self.value_dtype.type(0)
        out = []
        for k in keys:
            row = self._tier.get(int(k))
            out.append(self.value_dtype.type(row[0])
                       if row is not None else zero)
        return out

    # KVServer.store/load read self._store directly — snapshot through
    # the tier instead (same wire format, so snapshots interchange).
    def store(self, stream) -> None:
        items = sorted((int(k), self.value_dtype.type(row[0]))
                       for k, row in self._tier.items())
        stream.write(struct.pack("<q", len(items)))
        for k, v in items:
            stream.write(struct.pack("<q", k))
            stream.write(np.asarray(v, dtype=self.value_dtype).tobytes())

    def load(self, stream) -> None:
        (count,) = struct.unpack("<q", stream.read(8))
        self._tier.clear()
        item = self.value_dtype.itemsize
        for _ in range(count):
            (k,) = struct.unpack("<q", stream.read(8))
            v = np.frombuffer(stream.read(item), dtype=self.value_dtype)[0]
            self._tier.put(int(k), np.array([v], dtype=self.value_dtype))
        self._tier.maybe_maintain()

    def tier_stats(self) -> Dict[str, int]:
        return self._tier.stats()


class DeviceKVServer(ServerTable):
    """Hash-sharded device-resident KV store (see module docstring)."""

    def __init__(self, value_dtype: Any = np.float32,
                 capacity: int = 1 << 20) -> None:
        super().__init__()
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from multiverso_tpu.ops import device_hash
        from multiverso_tpu.parallel import mesh as mesh_lib
        from multiverso_tpu.runtime.zoo import Zoo

        zoo = Zoo.instance()
        self.value_dtype = np.dtype(value_dtype)
        if self.value_dtype.str not in ("<f4", "<i4"):
            log.fatal("DeviceKVServer values must be float32/int32 (JAX "
                      "x64-off); got %s — use the host KV table for wider "
                      "types", self.value_dtype)
        self.mesh = zoo.mesh
        self._axis = self.mesh.axis_names[0]
        # shards = the size of the ONE mesh axis the shard_map below indexes
        # (axis 0). On a multi-axis table mesh, devices off axis 0 replicate:
        # using zoo.num_servers (total device count) here would make
        # `key % num_shards == axis_index` silently drop every key with
        # residue >= the axis size.
        self.num_shards = int(self.mesh.shape[self._axis])
        # exact live count: hash_add reports newly-inserted slots per
        # batch (and rebuilds recount), so growth decisions never scan
        self._live = 0
        self._alloc(next_pow2(max(64, -(-int(capacity) // self.num_shards))))

    def _alloc(self, per: int) -> None:
        """(Re)allocate shard arrays at per-shard capacity ``per`` and
        rebuild the capacity-closed shard_map kernels (growth = fresh
        arrays + replay; the reference's unordered_map grew implicitly,
        kv_table.h:19-118)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from multiverso_tpu.ops import device_hash
        from multiverso_tpu.parallel import mesh as mesh_lib

        axis = self._axis
        self.shard_capacity = per
        self.capacity = per * self.num_shards
        sharding = mesh_lib.table_sharding(self.mesh, ndim=2, shard_dim=0,
                                           axis=axis)
        self.keys = jax.device_put(
            np.full((self.num_shards, per + 1), device_hash.EMPTY, np.int32),
            sharding)
        self.values = jax.device_put(
            np.zeros((self.num_shards, per + 1), self.value_dtype), sharding)

        num_shards = self.num_shards

        def add_body(keys_l, vals_l, bk, bv):
            idx = jax.lax.axis_index(axis)
            mine = (bk >= 0) & (bk % num_shards == idx)
            k2, v2, ovf, ins = device_hash.hash_add(
                keys_l[0], vals_l[0], jnp.where(mine, bk, -1),
                jnp.where(mine, bv, 0), per)
            # every live lane belongs to exactly one shard: the psums
            # yield the global per-lane overflow flags and the global
            # newly-inserted count, replicated
            return (k2[None], v2[None],
                    jax.lax.psum(ovf.astype(jnp.int32), axis),
                    jax.lax.psum(ins, axis))

        def get_body(keys_l, vals_l, bk):
            idx = jax.lax.axis_index(axis)
            mine = (bk >= 0) & (bk % num_shards == idx)
            out = device_hash.hash_get(
                keys_l[0], vals_l[0], jnp.where(mine, bk, -1), per)
            return jax.lax.psum(out, axis)

        self._add = jax.jit(jax.shard_map(
            add_body, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(), P()),
            out_specs=(P(axis), P(axis), P(), P())), donate_argnums=(0, 1))
        self._get = jax.jit(jax.shard_map(
            get_body, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P()), out_specs=P()))

    @staticmethod
    def _bucket(arr: np.ndarray, fill, dtype) -> np.ndarray:
        n = max(64, next_pow2(len(arr)))
        out = np.full(n, fill, dtype)
        out[: len(arr)] = arr
        return out

    def process_add(self, request) -> None:
        keys, values, _option = request
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        if keys.size and keys.min() < 0:
            log.fatal("DeviceKV keys must be >= 0")
        if keys.size and keys.max() >= (1 << 31):
            log.fatal("DeviceKV keys must fit int32")
        vals = np.asarray(values, dtype=self.value_dtype).reshape(-1)
        ukeys, inv = np.unique(keys.astype(np.int32), return_inverse=True)
        uvals = np.zeros(len(ukeys), self.value_dtype)
        np.add.at(uvals, inv, vals)
        self._insert(ukeys, uvals)

    def _insert(self, ukeys: np.ndarray, uvals: np.ndarray,
                depth: int = 0) -> None:
        """Insert unique (key, value) pairs, growing the table as needed.

        Proactive: if the exact live count plus this batch (worst case
        all-new) would push the load factor past 0.5, rebuild bigger
        FIRST. Reactive: probe exhaustion still flags unplaced lanes
        (values unapplied), which re-insert after a doubling rebuild —
        lossless by the hash_add contract."""
        import jax.numpy as jnp
        if depth > 8:
            log.fatal("DeviceKV growth did not converge after %d rebuilds "
                      "(capacity=%d, batch=%d)", depth, self.capacity,
                      len(ukeys))
        if 2 * (self._live + len(ukeys)) > self.capacity:
            self._grow(self._live + len(ukeys))
        bk = jnp.asarray(self._bucket(ukeys, -1, np.int32))
        bv = jnp.asarray(self._bucket(uvals, 0, self.value_dtype))
        self.keys, self.values, ovf, ins = self._add(self.keys, self.values,
                                                     bk, bv)
        # ONE host fetch for both scalars/flags: they are replicated
        # (out_specs P()), so a plain device_get is multihost-safe and a
        # second blocking round trip would be pure latency on the add path
        import jax
        ovf_h, ins_h = jax.device_get((ovf, ins))
        flags = np.asarray(ovf_h)[: len(ukeys)] > 0
        self._live += int(ins_h)
        if flags.any():
            # real probe exhaustion: force at least a doubling
            self._grow(self._live + int(flags.sum()), force_double=True)
            self._insert(ukeys[flags], uvals[flags], depth + 1)

    def _grow(self, need: int, force_double: bool = False) -> None:
        """Rebuild at a capacity giving >=4x headroom over ``need`` live
        keys and replay the live pairs (one jitted re-insert per rebuild;
        also recounts the live figure exactly).
        ``force_double`` (reactive overflow path) guarantees progress even
        when the headroom math alone would keep the same size."""
        import jax.numpy as jnp
        pairs = self.process_get((None, None))
        # 4x headroom (load <= 0.25): the batch claim protocol retries one
        # slot per probe round, so contention can exhaust MAX_PROBE well
        # before 0.5 load — sizing generously avoids rebuild churn (HBM
        # cost is two scalars per slot)
        per = next_pow2(max(
            64,
            -(-4 * max(need, len(pairs) + 1) // self.num_shards),
            2 * self.shard_capacity if force_double else 0))
        log.info("DeviceKV grow: %d live keys, capacity %d -> %d",
                 len(pairs), self.capacity, per * self.num_shards)
        self._alloc(per)
        self._live = len(pairs)
        if pairs:
            rk = np.fromiter(pairs.keys(), np.int32, len(pairs))
            rv = np.fromiter(pairs.values(), self.value_dtype, len(pairs))
            bk = jnp.asarray(self._bucket(rk, -1, np.int32))
            bv = jnp.asarray(self._bucket(rv, 0, self.value_dtype))
            self.keys, self.values, ovf, _ins = self._add(
                self.keys, self.values, bk, bv)
            if (self._host_read(ovf)[: len(rk)] > 0).any():
                # 4x headroom per shard should never exhaust 16 probes;
                # if the key distribution is that adversarial, stop
                log.fatal("DeviceKV rebuild overflowed its own replay "
                          "(%d keys, capacity %d)", len(rk), self.capacity)

    def process_get(self, request):
        import jax
        import jax.numpy as jnp
        keys, _option = request
        if keys is None:
            k = self._host_read(self.keys)[:, :-1].reshape(-1)
            v = self._host_read(self.values)[:, :-1].reshape(-1)
            live = k >= 0
            return {int(kk): self.value_dtype.type(vv)
                    for kk, vv in zip(k[live], v[live])}
        keys = np.asarray(keys, dtype=np.int32).reshape(-1)
        bk = jnp.asarray(self._bucket(keys, -1, np.int32))
        out = np.asarray(jax.device_get(self._get(self.keys, self.values, bk)))
        return list(out[: len(keys)])

    def remote_spec(self):
        return {"kind": "kv", "dtype": self.value_dtype.str}

    # -- checkpoint (live pairs only) ---------------------------------------
    def store(self, stream) -> None:
        pairs = self.process_get((None, None))
        items = sorted(pairs.items())
        stream.write(struct.pack("<q", len(items)))
        for k, v in items:
            stream.write(struct.pack("<q", int(k)))
            stream.write(np.asarray(v, dtype=self.value_dtype).tobytes())

    def load(self, stream) -> None:
        (count,) = struct.unpack("<q", stream.read(8))
        item = self.value_dtype.itemsize
        keys = np.empty(count, np.int64)
        vals = np.empty(count, self.value_dtype)
        for i in range(count):
            (keys[i],) = struct.unpack("<q", stream.read(8))
            vals[i] = np.frombuffer(stream.read(item),
                                    dtype=self.value_dtype)[0]
        # reset (fresh arrays + kernels) and replay through the growing
        # insert path — a snapshot larger than the current capacity
        # simply triggers a rebuild
        self._alloc(self.shard_capacity)
        self._live = 0
        if count:
            self.process_add((keys, vals, None))


class KVWorker(WorkerTable):
    """Client proxy with a local cache (reference: ``raw()``). Pass
    ``capacity=N`` for the device-resident hash-sharded backend."""

    def __init__(self, value_dtype: Any = np.float32,
                 capacity: Optional[int] = None,
                 server: Optional[ServerTable] = None) -> None:
        super().__init__()
        self.value_dtype = np.dtype(value_dtype)
        if server is not None:
            self._server_table = server
        elif capacity is not None:
            self._server_table = DeviceKVServer(value_dtype, capacity)
        else:
            self._server_table = KVServer(value_dtype)
        self._register(self._server_table)
        self._raw: Dict[int, Any] = {}

    def raw(self) -> Dict[int, Any]:
        return self._raw

    def get(self, keys: Union[int, Iterable[int], None] = None):
        single = isinstance(keys, (int, np.integer))
        norm = None if keys is None else ([int(keys)] if single else [int(k) for k in keys])
        result = super().get((norm, None))
        if norm is None:
            self._raw = result
            return dict(result)
        for k, v in zip(norm, result):
            self._raw[k] = v
        return result[0] if single else result

    def add(self, keys: Union[int, Iterable[int]], values) -> None:
        norm, vals = self._normalize(keys, values)
        super().add((norm, vals, None))

    def add_async(self, keys, values) -> int:
        norm, vals = self._normalize(keys, values)
        return super().add_async((norm, vals, None))

    def _normalize(self, keys, values) -> Tuple[list, list]:
        if isinstance(keys, (int, np.integer)):
            return [int(keys)], [self.value_dtype.type(values)]
        norm = [int(k) for k in keys]
        vals = [self.value_dtype.type(v) for v in values]
        return norm, vals


def make_tiered_kv(value_dtype: Any = np.float32,
                   **tier_kwargs: Any) -> KVWorker:
    """Factory for ``register_table_type("tiered_kv", ...)``: a KVWorker
    served by a beyond-RAM :class:`TieredKVServer` (``tier_kwargs``:
    resident_bytes / cold_bits / tier_dir / admit_touches; defaults come
    from the ``tier_*`` flags)."""
    return KVWorker(value_dtype, server=TieredKVServer(value_dtype,
                                                       **tier_kwargs))
