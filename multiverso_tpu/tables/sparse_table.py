"""Sparse-key tables — arbitrary integer keys, vector values, O(nnz) traffic.

Reference capability (not copied): LogisticRegression's custom user tables —
``SparseWorkerTable/SparseServerTable`` (arbitrary ``size_t`` keys over a
huge key space, range-sharded, Add ships ONLY touched entries, Get-all
returns only live entries; ``Applications/LogisticRegression/src/util/
sparse_table.h:17-168``) and the struct-valued FTRL variant where the server
stores ``FTRLGradient{z,n}`` per key and Get materializes FTRL-proximal
weights (``util/ftrl_sparse_table.h:12-90`` over ``util/hopscotch_hash.h``).

TPU-era design: this is the *high-dimensional sparse-model* table (the
lightLDA/CTR shape) — key spaces of 1e8+ where a dense HBM array would waste
memory ∝ key space instead of ∝ live keys. The host dict IS the hash table
(the reference's hopscotch map re-founded on the host control plane); traffic
is the resource that matters and it is O(nnz) in both directions:

* ``add(keys, values)`` ships exactly the touched entries; the server applies
  the linear updater sign (default ``+=`` / sgd ``-=``) vectorized over the
  batch.
* ``get(keys)`` returns exactly those rows (missing keys read as zero —
  the reference's DataBlock semantics).
* ``get()`` (all) returns ``(live_keys, values)`` — size ∝ live keys, never
  ∝ key space.

Values are width-W float32 rows (W = e.g. the softmax output count), so one
key carries a whole output column — the struct-valued entry generalized.

The dense-key/device path remains :class:`~multiverso_tpu.tables.kv_table.
DeviceKVServer` (scalar HBM hash) and MatrixTable (dense rows); this table
trades device residency for unbounded key spaces, exactly the trade the
reference's app-level tables made against its core ArrayTable.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterable, Optional, Tuple, Union

import numpy as np

from multiverso_tpu import log
from multiverso_tpu.tables.base import ServerTable, WorkerTable
from multiverso_tpu.updaters import SGDUpdater, Updater, get_updater


class SparseServer(ServerTable):
    """Hash-map server: key -> (width,) float32 row, created on first touch."""

    def __init__(self, key_space: int, width: int = 1,
                 dtype: Any = np.float32, updater_type: str = "") -> None:
        super().__init__()
        self.key_space = int(key_space)
        self.width = int(width)
        self.dtype = np.dtype(dtype)
        updater = get_updater(self.dtype, updater_type)
        if type(updater) not in (Updater, SGDUpdater):
            log.fatal("sparse table supports linear updaters (default/sgd); "
                      "use the sparse_ftrl table for stateful optimization")
        self._sign = -1.0 if isinstance(updater, SGDUpdater) else 1.0
        self._store: Dict[int, np.ndarray] = {}

    def _check_keys(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        if keys.size and (keys.min() < 0 or keys.max() >= self.key_space):
            log.fatal("sparse key out of range [0, %d)", self.key_space)
        return keys

    def process_add(self, request) -> None:
        keys, values, _option = request
        keys = self._check_keys(keys)
        values = np.asarray(values, dtype=self.dtype).reshape(-1, self.width)
        if len(keys) != len(values):
            log.fatal("sparse.add: %d keys but %d value rows",
                      len(keys), len(values))
        sign = self._sign
        store = self._store
        for k, v in zip(keys.tolist(), values):
            row = store.get(k)
            if row is None:
                store[k] = sign * v.copy()
            else:
                row += sign * v

    def process_get(self, request):
        keys, _option = request
        if keys is None:
            # get-all: live entries only (reference Get(DataBlock*) semantics)
            live = np.fromiter(self._store.keys(), dtype=np.int64,
                               count=len(self._store))
            live.sort()
            vals = (np.stack([self._store[k] for k in live.tolist()])
                    if len(live) else np.zeros((0, self.width), self.dtype))
            return live, vals
        keys = self._check_keys(keys)
        out = np.zeros((len(keys), self.width), self.dtype)
        for i, k in enumerate(keys.tolist()):
            row = self._store.get(k)
            if row is not None:
                out[i] = row
        return out

    def remote_spec(self):
        return {"kind": "sparse", "key_space": self.key_space,
                "width": self.width, "dtype": self.dtype.str}

    # -- checkpoint ---------------------------------------------------------
    def store(self, stream) -> None:
        live, vals = self.process_get((None, None))
        stream.write(struct.pack("<qq", len(live), self.width))
        stream.write(live.astype(np.int64).tobytes())
        stream.write(vals.astype(self.dtype).tobytes())

    def load(self, stream) -> None:
        count, width = struct.unpack("<qq", stream.read(16))
        if width != self.width:
            log.fatal("sparse.load: width %d != %d", width, self.width)
        keys = np.frombuffer(stream.read(8 * count), dtype=np.int64)
        vals = np.frombuffer(stream.read(self.dtype.itemsize * count * width),
                             dtype=self.dtype).reshape(count, width)
        self._store = {int(k): v.copy() for k, v in zip(keys, vals)}


class SparseFTRLServer(ServerTable):
    """Struct-valued sparse server: per-key FTRL accumulators ``(z, n)``;
    Add ships raw gradient rows, Get derives FTRL-proximal weights — the
    server never stores stale w (reference: ``ftrl_sparse_table.h`` entries;
    same closed form as the dense :mod:`~multiverso_tpu.tables.ftrl_table`)."""

    def __init__(self, key_space: int, width: int = 1, alpha: float = 0.1,
                 beta: float = 1.0, lambda1: float = 1.0,
                 lambda2: float = 1.0) -> None:
        super().__init__()
        self.key_space = int(key_space)
        self.width = int(width)
        self.dtype = np.dtype(np.float32)
        self.alpha, self.beta = float(alpha), float(beta)
        self.lambda1, self.lambda2 = float(lambda1), float(lambda2)
        self._z: Dict[int, np.ndarray] = {}
        self._n: Dict[int, np.ndarray] = {}

    def _weights(self, z: np.ndarray, n: np.ndarray) -> np.ndarray:
        shrunk = np.sign(z) * np.maximum(np.abs(z) - self.lambda1, 0.0)
        denom = (self.beta + np.sqrt(n)) / self.alpha + self.lambda2
        return (-shrunk / denom).astype(np.float32)

    def process_add(self, request) -> None:
        keys, grads, _option = request
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        grads = np.asarray(grads, dtype=np.float32).reshape(-1, self.width)
        for k, g in zip(keys.tolist(), grads):
            z = self._z.get(k)
            if z is None:
                z = np.zeros(self.width, np.float32)
                n = np.zeros(self.width, np.float32)
            else:
                n = self._n[k]
            w = self._weights(z, n)
            sigma = (np.sqrt(n + g * g) - np.sqrt(n)) / self.alpha
            self._z[k] = z + g - sigma * w
            self._n[k] = n + g * g

    def process_get(self, request):
        keys, _option = request
        if keys is None:
            live = np.fromiter(self._z.keys(), dtype=np.int64,
                               count=len(self._z))
            live.sort()
            vals = (np.stack([self._weights(self._z[k], self._n[k])
                              for k in live.tolist()])
                    if len(live) else np.zeros((0, self.width), np.float32))
            return live, vals
        keys = np.asarray(keys, dtype=np.int64).reshape(-1)
        out = np.zeros((len(keys), self.width), np.float32)
        for i, k in enumerate(keys.tolist()):
            z = self._z.get(k)
            if z is not None:
                out[i] = self._weights(z, self._n[k])
        return out

    def remote_spec(self):
        return {"kind": "sparse", "key_space": self.key_space,
                "width": self.width, "dtype": self.dtype.str}

    def store(self, stream) -> None:
        live = np.array(sorted(self._z.keys()), dtype=np.int64)
        stream.write(struct.pack("<qq", len(live), self.width))
        stream.write(live.tobytes())
        for k in live.tolist():
            stream.write(self._z[k].tobytes())
            stream.write(self._n[k].tobytes())

    def load(self, stream) -> None:
        count, width = struct.unpack("<qq", stream.read(16))
        if width != self.width:
            log.fatal("sparse_ftrl.load: width %d != %d", width, self.width)
        keys = np.frombuffer(stream.read(8 * count), dtype=np.int64)
        self._z, self._n = {}, {}
        row = 4 * width
        for k in keys.tolist():
            self._z[k] = np.frombuffer(stream.read(row), np.float32).copy()
            self._n[k] = np.frombuffer(stream.read(row), np.float32).copy()


class TieredSparseServer(SparseServer):
    """SparseServer whose row store is hot/cold tiered (multiverso_tpu/
    store/, docs/tiered_storage.md): hot rows stay dict-resident under
    ``tier_resident_bytes``; the LRU tail spills to quantized CRC-framed
    segments on disk and promotes back on access through a frequency-
    sketch admission filter.

    Everything above the store is unchanged — ``remote_spec`` still says
    ``kind=sparse``, so remote proxies, the shard router, warm standbys
    and live resharding treat a tiered table exactly like an in-RAM one.
    Demotion runs inside ``process_add`` (dispatcher-serialized, after
    the WAL append that ordered the triggering Add), so recovery replay
    reproduces the same logical state whatever instant a crash hits."""

    def __init__(self, key_space: int, width: int = 1,
                 dtype: Any = np.float32, updater_type: str = "",
                 resident_bytes: Optional[int] = None,
                 cold_bits: Optional[int] = None,
                 tier_dir: Optional[str] = None,
                 admit_touches: Optional[int] = None) -> None:
        super().__init__(key_space, width, dtype, updater_type)
        from multiverso_tpu.store import TieredStore
        self._tier = TieredStore(self.width, self.dtype,
                                 resident_bytes=resident_bytes,
                                 cold_bits=cold_bits, directory=tier_dir,
                                 admit_touches=admit_touches)
        self._store = None  # any missed base-class path must fail loudly

    def process_add(self, request) -> None:
        keys, values, _option = request
        keys = self._check_keys(keys)
        values = np.asarray(values, dtype=self.dtype).reshape(-1, self.width)
        if len(keys) != len(values):
            log.fatal("sparse.add: %d keys but %d value rows",
                      len(keys), len(values))
        signed = np.asarray(self._sign * values, dtype=self.dtype)
        tier = self._tier
        for k, v in zip(keys.tolist(), signed):
            row = tier.get_for_update(k)
            if row is None:
                tier.put(k, v.copy())
            else:
                row += v
        tier.maybe_maintain()

    def process_get(self, request):
        keys, _option = request
        if keys is None:
            snap = dict(self._tier.items())
            live = np.fromiter(snap.keys(), dtype=np.int64, count=len(snap))
            live.sort()
            vals = (np.stack([snap[k] for k in live.tolist()])
                    if len(live) else np.zeros((0, self.width), self.dtype))
            return live, vals
        keys = self._check_keys(keys)
        out = np.zeros((len(keys), self.width), self.dtype)
        for i, k in enumerate(keys.tolist()):
            row = self._tier.get(k)
            if row is not None:
                out[i] = row
        return out

    # store() is inherited: it snapshots via process_get((None, None)).
    def load(self, stream) -> None:
        count, width = struct.unpack("<qq", stream.read(16))
        if width != self.width:
            log.fatal("sparse.load: width %d != %d", width, self.width)
        keys = np.frombuffer(stream.read(8 * count), dtype=np.int64)
        vals = np.frombuffer(stream.read(self.dtype.itemsize * count * width),
                             dtype=self.dtype).reshape(count, width)
        self._tier.clear()
        for k, v in zip(keys.tolist(), vals):
            self._tier.put(k, v.copy())
        self._tier.maybe_maintain()  # a beyond-RAM snapshot re-tiers on load

    def tier_stats(self) -> Dict[str, int]:
        return self._tier.stats()


class SparseWorker(WorkerTable):
    """Client proxy: O(nnz) get/add over arbitrary integer keys.

    ``get(keys)`` -> (N, W) rows; ``get()`` -> (live_keys, values);
    ``add(keys, values)`` ships exactly the touched entries. Counters
    ``elements_pushed`` / ``elements_pulled`` make the O(nnz) contract
    testable.
    """

    def __init__(self, key_space: int, width: int = 1,
                 dtype: Any = np.float32, updater_type: str = "",
                 ftrl: bool = False, server: Optional[ServerTable] = None,
                 **ftrl_kwargs: Any) -> None:
        super().__init__()
        self.key_space = int(key_space)
        self.width = int(width)
        self.dtype = np.dtype(dtype)
        if server is not None:
            self._server_table = server
        elif ftrl:
            self._server_table = SparseFTRLServer(key_space, width,
                                                  **ftrl_kwargs)
        else:
            self._server_table = SparseServer(key_space, width, dtype,
                                              updater_type)
        self._register(self._server_table)
        self.elements_pushed = 0
        self.elements_pulled = 0

    def _norm_keys(self, keys) -> Optional[np.ndarray]:
        if keys is None:
            return None
        return np.asarray(keys, dtype=np.int64).reshape(-1)

    def get(self, keys: Optional[Iterable[int]] = None
            ) -> Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]:
        raw = super().get((self._norm_keys(keys), None))
        if keys is None:
            self.elements_pulled += int(raw[1].size)
        else:
            self.elements_pulled += int(raw.size)
        return raw

    def get_async(self, keys: Optional[Iterable[int]] = None) -> int:
        return super().get_async((self._norm_keys(keys), None))

    def add(self, keys: Iterable[int], values: np.ndarray) -> None:
        keys = self._norm_keys(keys)
        values = np.asarray(values, dtype=self.dtype)
        self.elements_pushed += int(values.size)
        super().add((keys, values, None))

    def add_async(self, keys: Iterable[int], values: np.ndarray) -> int:
        keys = self._norm_keys(keys)
        values = np.asarray(values, dtype=self.dtype)
        self.elements_pushed += int(values.size)
        return super().add_async((keys, values, None))


def make_sparse_ftrl(key_space: int, width: int = 1, **kwargs: Any
                     ) -> SparseWorker:
    """Factory for ``register_table_type("sparse_ftrl", ...)``."""
    return SparseWorker(key_space, width, ftrl=True, **kwargs)


def make_tiered_sparse(key_space: int, width: int = 1,
                       dtype: Any = np.float32, updater_type: str = "",
                       **tier_kwargs: Any) -> SparseWorker:
    """Factory for ``register_table_type("tiered_sparse", ...)``:
    a SparseWorker served by a beyond-RAM :class:`TieredSparseServer`
    (``tier_kwargs``: resident_bytes / cold_bits / tier_dir /
    admit_touches; defaults come from the ``tier_*`` flags)."""
    server = TieredSparseServer(key_space, width, dtype, updater_type,
                                **tier_kwargs)
    return SparseWorker(key_space, width, dtype, updater_type, server=server)
