"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention (its long-context axis was table size, not
sequence length — SURVEY §5), but the table-sharding seam it leaves open
(`PartitionSpec` over rows) is exactly where a sequence axis attaches. This
module provides the two standard TPU-native long-sequence strategies over a
mesh axis, so models built on this framework scale sequence length across
chips the way tables already scale parameter count:

* :func:`ring_attention` — blockwise attention with the K/V shards rotating
  around the mesh axis via ``lax.ppermute`` (one neighbor hop per step, so
  the traffic rides ICI), accumulated with a streaming numerically-stable
  softmax (the flash/online-softmax recurrence). Peak memory per chip is
  O(T_local² · heads) instead of O(T²), and K/V transfers overlap compute
  chunk-for-chunk under XLA's latency-hiding scheduler.
* :func:`ulysses_all_to_all` — the all-to-all reshard between
  sequence-parallel layout (heads replicated, sequence split) and
  head-parallel layout (sequence replicated locally, heads split), which
  turns any single-device attention kernel into a sequence-parallel one
  when the head count divides the axis size.

Both are plain traceable functions meant for use inside ``shard_map`` over
a ``Mesh`` axis; see ``tests/test_ring.py`` for the exact-equality harness
against full-sequence attention on the virtual 8-device mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_scores(q, k, scale):
    # q: (B, Tq, H, D), k: (B, Tk, H, D) -> (B, H, Tq, Tk)
    return jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   q_offset: Optional[jax.Array] = None,
                   bias_fn=None) -> jax.Array:
    """Blockwise ring attention over mesh axis ``axis_name``.

    Args:
      q, k, v: per-shard ``(B, T_local, H, D)`` blocks of a global
        ``(B, T, H, D)`` sequence sharded on T. Call inside ``shard_map``
        with T mapped over ``axis_name``.
      causal: apply a causal mask using GLOBAL positions (shard i's tokens
        occupy ``[i*T_local, (i+1)*T_local)``; contiguous sharding assumed).
      q_offset: optional per-shard global offset of this block's first
        query token; defaults to ``axis_index * T_local``.
      bias_fn: optional ``bias_fn(q_pos, kv_pos) -> bias`` called once per
        ring step with the GLOBAL query/key position vectors ``(Tq,)`` /
        ``(Tk,)``; the returned bias (broadcastable to ``(B, H, Tq, Tk)``,
        e.g. a T5-style relative-position table lookup) is added to the
        scores before the softmax. Runs per block, so no (T, T) bias is
        ever materialized.

    Returns: the attention output block ``(B, T_local, H, D)``, exactly
    equal (up to float assoc.) to slicing full-sequence attention.

    The K/V block makes ``axis_size`` hops around the ring; each step
    contracts the local queries against one remote block and folds the
    result into an online-softmax accumulator ``(m, l, o)`` — running max,
    running normalizer, running unnormalized output — so no step ever
    materializes the full (T, T) score matrix.
    """
    axis_size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    if q_offset is None:
        q_offset = idx * T

    q_pos = q_offset + jnp.arange(T)  # (T,) global query positions

    def step(carry, _):
        k_blk, v_blk, kv_idx, m, l, o = carry
        s = _block_scores(q, k_blk, scale)  # (B, H, Tq, Tk)
        kv_pos = kv_idx * T + jnp.arange(T)  # global key positions
        if bias_fn is not None:
            s = s + bias_fn(q_pos, kv_pos)
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]  # (Tq, Tk)
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))  # (B, H, Tq)
        # exp(-inf - -inf) guards: where m_new is still -inf (no visible
        # key yet), keep p at 0 and the correction factor at 1
        corr = jnp.where(jnp.isneginf(m), jnp.where(jnp.isneginf(m_new),
                                                    1.0, 0.0),
                         jnp.exp(m - m_new))
        p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0,
                      jnp.exp(s - m_new[..., None]))
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        # rotate K/V one hop around the ring (ICI neighbor traffic)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        kv_nxt = lax.ppermute(kv_idx, axis_name, perm)
        return (k_nxt, v_nxt, kv_nxt, m_new, l, o), None

    m0 = jnp.full((B, H, T), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, T), q.dtype)
    o0 = jnp.zeros((B, H, T, D), q.dtype)
    # mark the constant init as device-varying so the scan carry type
    # matches its (axis-varying) outputs under shard_map's vma check
    if hasattr(lax, "pcast"):
        m0, l0, o0 = (lax.pcast(x, axis_name, to="varying")
                      for x in (m0, l0, o0))
    elif hasattr(lax, "pvary"):
        m0, l0, o0 = (lax.pvary(x, axis_name) for x in (m0, l0, o0))
    (_, _, _, m, l, o), _ = lax.scan(
        step, (k, v, idx, m0, l0, o0), None, length=axis_size)
    out = o / jnp.maximum(l, 1e-30)[..., None]  # (B, H, Tq, D)
    return jnp.einsum("bhqd->bqhd", out)


def ulysses_all_to_all(x: jax.Array, axis_name: str,
                       to_heads: bool = True) -> jax.Array:
    """Ulysses reshard between sequence-split and head-split layouts.

    With axis size N and per-shard ``(B, T_local, H, D)``:

    * ``to_heads=True``: gather the FULL sequence for H/N heads —
      returns ``(B, T_local * N, H // N, D)``. Any single-device attention
      kernel then runs unchanged on its head slice.
    * ``to_heads=False``: the inverse, back to ``(B, T_local, H, D)``.

    The axis size must divide the head count (H % N == 0 — each shard
    takes H/N heads). One ``lax.all_to_all`` each way — the Ulysses
    communication pattern.
    """
    n = lax.psum(1, axis_name)
    if to_heads:
        H = x.shape[2]
        if isinstance(n, int) and H % n != 0:
            raise ValueError(
                f"ulysses_all_to_all: the '{axis_name}' axis size {n} "
                f"must divide the head count {H} (each shard takes "
                f"H/{n} heads)")
        # split heads into N groups, exchange so each shard holds all T of
        # one group: concat_axis=time, split_axis=heads
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False, bias_fn=None) -> jax.Array:
    """Full-sequence single-device attention (the correctness oracle for
    the parallel paths; also usable per head-slice after a Ulysses
    reshard). Shapes ``(B, T, H, D)``."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t = q.shape[1]
    if bias_fn is not None:
        pos = jnp.arange(t)
        s = s + bias_fn(pos, pos)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return jnp.einsum("bhqd->bqhd", out)
