"""Parallelism substrate: device meshes/shardings (:mod:`.mesh`) and
sequence/context parallelism (:mod:`.ring` — ring attention + Ulysses)."""

from multiverso_tpu.parallel.mesh import (build_mesh, parse_mesh_shape,
                                          replicated, table_sharding)
from multiverso_tpu.parallel.ring import (reference_attention, ring_attention,
                                          ulysses_all_to_all)

__all__ = [
    "build_mesh", "parse_mesh_shape", "replicated", "table_sharding",
    "reference_attention", "ring_attention", "ulysses_all_to_all",
]
