"""Device mesh construction and sharding helpers.

This is the substrate that replaces the reference's rank topology: server
"shards" are device shards of a :class:`jax.sharding.Mesh` axis instead of
MPI ranks (reference range sharding: ``src/table/array_table.cpp:13-19``,
``src/table/matrix_table.cpp:25-45``).

Design: one global *table mesh* (axis ``server``) owns parameter-table
placement; applications build richer meshes (data/model/pipeline axes) for
their own compute and the tables interoperate because Get/Add results cross
via host or via resharding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_mesh_shape(text: str) -> Optional[Tuple[int, ...]]:
    """Parse '2x4'-style mesh shape flags; empty → None (auto 1-D)."""
    text = text.strip()
    if not text:
        return None
    return tuple(int(tok) for tok in text.replace("*", "x").split("x"))


def build_mesh(devices: Optional[Sequence[jax.Device]] = None,
               shape: Optional[Tuple[int, ...]] = None,
               axis_names: Sequence[str] = ("server",)) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names))


def table_sharding(mesh: Mesh, ndim: int, shard_dim: int = 0,
                   axis: str = "server") -> NamedSharding:
    """Sharding for a table state array: dimension ``shard_dim`` split over
    the server axis (reference analog: range sharding over server ranks)."""
    spec = [None] * ndim
    spec[shard_dim] = axis
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    return NamedSharding(mesh, P(*([None] * ndim)))


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k that is >= n (shard-divisibility padding)."""
    return ((n + k - 1) // k) * k


def shard_ranges(total: int, num_shards: int) -> List[Tuple[int, int]]:
    """Equal-chunk ranges with remainder to the last shard — mirrors the
    reference's server offset computation so `server_id`-indexed APIs
    (e.g. checkpoint-per-shard naming) agree with its layout."""
    chunk = total // num_shards
    ranges = []
    for i in range(num_shards):
        begin = chunk * i
        end = total if i == num_shards - 1 else chunk * (i + 1)
        ranges.append((begin, end))
    return ranges
