"""Leveled logger + CHECK macros — capability parity with the reference logger.

Reference capability (not copied): a leveled (Debug/Info/Error/Fatal) logger
with a static facade, optional file sink, and ``CHECK``/``CHECK_NOTNULL``
macros that fatal on failure (``include/multiverso/util/log.h:9-142``).

TPU-era notes: Fatal raises :class:`FatalError` instead of aborting the
process by default (a JAX host process may own device buffers that deserve
cleanup); ``set_kill_on_fatal(True)`` restores abort semantics for
drop-in-compatible hosts.
"""

from __future__ import annotations

import enum
import os
import sys
import threading
import time
from typing import Any, Optional, TextIO


class LogLevel(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    ERROR = 2
    FATAL = 3


class FatalError(RuntimeError):
    """Raised by Log.fatal / failed CHECKs when kill_on_fatal is off."""


class Logger:
    def __init__(self, level: LogLevel = LogLevel.INFO) -> None:
        self._level = level
        self._file: Optional[TextIO] = None
        self._lock = threading.Lock()
        self._kill_on_fatal = False

    def reset_log_level(self, level: LogLevel) -> None:
        self._level = level

    def reset_log_file(self, filename: str = "") -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            if filename:
                self._file = open(filename, "a", encoding="utf-8")

    def set_kill_on_fatal(self, kill: bool) -> None:
        self._kill_on_fatal = kill

    def _emit(self, level: LogLevel, msg: str) -> None:
        if level < self._level:
            return
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())
        line = f"[{level.name}] [{stamp}] [pid:{os.getpid()}] {msg}"
        with self._lock:
            stream = sys.stderr if level >= LogLevel.ERROR else sys.stdout
            print(line, file=stream, flush=True)
            if self._file is not None:
                print(line, file=self._file, flush=True)

    def debug(self, fmt: str, *args: Any) -> None:
        self._emit(LogLevel.DEBUG, fmt % args if args else fmt)

    def info(self, fmt: str, *args: Any) -> None:
        self._emit(LogLevel.INFO, fmt % args if args else fmt)

    def error(self, fmt: str, *args: Any) -> None:
        self._emit(LogLevel.ERROR, fmt % args if args else fmt)

    def fatal(self, fmt: str, *args: Any) -> None:
        msg = fmt % args if args else fmt
        self._emit(LogLevel.FATAL, msg)
        if self._kill_on_fatal:
            os._exit(1)
        raise FatalError(msg)


# Static facade (reference: `Log` static class).
LOG = Logger()
debug = LOG.debug
info = LOG.info
error = LOG.error
fatal = LOG.fatal
reset_log_level = LOG.reset_log_level
reset_log_file = LOG.reset_log_file


def check(condition: Any, msg: str = "CHECK failed") -> None:
    """``CHECK(cond)`` parity: fatal when the condition is falsy."""
    if not condition:
        LOG.fatal(msg)


def check_notnull(value: Any, name: str = "pointer") -> Any:
    if value is None:
        LOG.fatal(f"CHECK_NOTNULL failed: {name} is None")
    return value
