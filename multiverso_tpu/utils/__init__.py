"""Host-side concurrency primitives for the TPU runtime.

Reference capabilities re-founded here (not copied):
* ``MtQueue`` — blocking MPMC queue with Exit poison for shutdown
  (``include/multiverso/util/mt_queue.h:18-145``).
* ``Waiter`` — counted latch for outstanding-reply tracking
  (``include/multiverso/util/waiter.h:9-33``).
* ``ASyncBuffer`` — generic double-buffer prefetcher
  (``include/multiverso/util/async_buffer.h:10-116``).

These back the host-side dispatcher that replaces the reference's actor
threads; the device-side data path is pure XLA and never touches them.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Generic, Optional, TypeVar

T = TypeVar("T")


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n<=1 -> 1) — the shared bucket rounding
    used by table id-batches, compact PS models, and KV capacities."""
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class MtQueue(Generic[T]):
    """Blocking multi-producer/multi-consumer queue with exit poison."""

    def __init__(self) -> None:
        self._items: Deque[T] = deque()
        self._mutex = threading.Lock()
        self._nonempty = threading.Condition(self._mutex)
        self._alive = True

    def push(self, item: T) -> None:
        with self._nonempty:
            self._items.append(item)
            self._nonempty.notify()

    def pop(self) -> Optional[T]:
        """Blocking pop; returns None once Exit() is called and queue drains."""
        with self._nonempty:
            while not self._items and self._alive:
                self._nonempty.wait()
            if self._items:
                return self._items.popleft()
            return None

    def pop_all(self) -> Optional[list]:
        """Blocking drain: wait like :meth:`pop`, then return EVERY queued
        item at once (arrival order). None once Exit() is called and the
        queue is empty — same shutdown contract as ``pop``. This is the
        dispatcher's micro-batching primitive: one wakeup hands the server
        the whole backlog so compatible Adds can fuse into a single device
        apply instead of paying per-message dispatch."""
        with self._nonempty:
            while not self._items and self._alive:
                self._nonempty.wait()
            if not self._items:
                return None
            items = list(self._items)
            self._items.clear()
            return items

    def try_pop(self) -> Optional[T]:
        with self._mutex:
            if self._items:
                return self._items.popleft()
            return None

    def front(self) -> Optional[T]:
        with self._mutex:
            return self._items[0] if self._items else None

    def empty(self) -> bool:
        with self._mutex:
            return not self._items

    def size(self) -> int:
        with self._mutex:
            return len(self._items)

    def exit(self) -> None:
        with self._nonempty:
            self._alive = False
            self._nonempty.notify_all()

    @property
    def alive(self) -> bool:
        return self._alive


class Waiter:
    """Counted latch: ``wait()`` blocks until ``notify()`` called N times."""

    def __init__(self, num_wait: int = 1) -> None:
        self._mutex = threading.Lock()
        self._cond = threading.Condition(self._mutex)
        self._num = num_wait

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: self._num <= 0, timeout)

    def notify(self) -> None:
        with self._cond:
            self._num -= 1
            if self._num <= 0:
                self._cond.notify_all()

    def reset(self, num_wait: int) -> None:
        with self._cond:
            self._num = num_wait


class AsyncBuffer(Generic[T]):
    """Double-buffer prefetcher: a background thread fills the non-current
    buffer with ``fill(buffer) -> value``; ``get()`` waits, swaps, re-prefetches.
    """

    def __init__(self, buffer0: T, buffer1: T, fill: Callable[[T], None]) -> None:
        self._buffers = [buffer0, buffer1]
        self._fill = fill
        self._current = 0
        self._ready = Waiter(1)
        self._queue: MtQueue[int] = MtQueue()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self._queue.push(self._current)

    def _loop(self) -> None:
        while True:
            idx = self._queue.pop()
            if idx is None:
                return
            try:
                self._fill(self._buffers[idx])
            except BaseException as exc:  # surface in get(), don't die silent
                self._error = exc
                self._ready.notify()
                return
            self._ready.notify()

    def get(self) -> T:
        self._ready.wait()
        if self._error is not None:
            raise RuntimeError("AsyncBuffer fill failed") from self._error
        filled = self._current
        self._current = 1 - self._current
        self._ready.reset(1)
        self._queue.push(self._current)
        return self._buffers[filled]

    def stop(self) -> None:
        self._queue.exit()
        self._thread.join(timeout=5)


def async_upload(x):
    """Host->device transfer that ENQUEUES and returns immediately with a
    future-backed array (~0.1 ms), where ``jnp.asarray`` blocks a fixed
    full tunnel round trip per call (~26 ms measured on tunneled chips,
    independent of size). The rule for every hot-path numpy upload; the
    input must not be mutated after the call (the copy is in flight)."""
    import jax
    return jax.device_put(x)
