"""Wire compression: SparseFilter + the quantized delta codec (the
OneBits slot) with error feedback.

Reference capability (not copied): ``SparseFilter<data,index>`` encodes a
blob as (index, value) pairs when >50% zeros, with a size side-channel;
``OneBitsFilter`` — the 1-bit-SGD wire codec the DMTK era was known for —
was an empty stub (``include/multiverso/util/quantization_util.h:37-161``).
Implemented for real here: deltas quantize to 1/2/4/8 bits per value with
client-side residual accumulation (error feedback), so the quantization
error feeds into the next push instead of being lost — the property that
makes 1-bit SGD converge.

TPU-era role: only host hops (C-API bridge, external clients) benefit —
on-mesh traffic is XLA collectives. Codecs are native C++
(``native/sparse_filter.cpp``, ``native/quant_filter.cpp``) loaded via
ctypes, with pure numpy fallbacks producing byte-identical output
(magics 'MVSF' / 'MVQF'). Quantization scale derivation uses only
order-independent reductions (min/max), so native and numpy agree
bit-for-bit; the elementwise quantize/dequantize is float32 with
round-half-to-even on both sides.
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Optional

import numpy as np

_MAGIC = 0x4653564D  # 'MVSF'

_native: Optional[ctypes.CDLL] = None
_native_load_attempted = False


def _load_native() -> Optional[ctypes.CDLL]:
    # cache failure too: without the .so built, retrying dlopen on every
    # encode/decode would tax the hot wire-compression path
    global _native, _native_load_attempted
    if _native_load_attempted:
        return _native
    _native_load_attempted = True
    path = os.path.join(os.path.dirname(__file__), "..", "native",
                        "libmultiverso_tpu.so")
    try:
        lib = ctypes.CDLL(os.path.abspath(path))
        # size_t SparseEncodeC(const float*, size_t, uint8_t*, size_t)
        lib.MVTPU_SparseEncode.restype = ctypes.c_size_t
        lib.MVTPU_SparseEncode.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        lib.MVTPU_SparseDecode.restype = ctypes.c_int
        lib.MVTPU_SparseDecode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t]
        _native = lib
    except (OSError, AttributeError):
        _native = None
    return _native


def sparse_encode(data: np.ndarray, force_numpy: bool = False) -> bytes:
    """Encode a float32 array; sparse form when <50% nonzero."""
    data = np.ascontiguousarray(data, dtype=np.float32).reshape(-1)
    lib = None if force_numpy else _load_native()
    if lib is not None:
        # worst case: header(16) + nnz(8) + count*(4+4)
        cap = 24 + data.size * 8
        out = np.empty(cap, dtype=np.uint8)
        n = lib.MVTPU_SparseEncode(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), data.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
        return out[:n].tobytes()
    nz = np.nonzero(data)[0]
    sparse = 2 * len(nz) < data.size
    header = struct.pack("<IIQ", _MAGIC, 1 if sparse else 0, data.size)
    if not sparse:
        return header + data.tobytes()
    pairs = np.empty((len(nz), 2), dtype=np.uint32)
    pairs[:, 0] = nz.astype(np.uint32)
    pairs[:, 1] = data[nz].view(np.uint32)
    return header + struct.pack("<Q", len(nz)) + pairs.tobytes()


def sparse_decode(payload: bytes, count: int,
                  force_numpy: bool = False) -> np.ndarray:
    lib = None if force_numpy else _load_native()
    if lib is not None:
        out = np.zeros(count, dtype=np.float32)
        buf = np.frombuffer(payload, dtype=np.uint8)
        ok = lib.MVTPU_SparseDecode(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(payload),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), count)
        if not ok:
            raise ValueError("malformed sparse payload")
        return out
    magic, kind, n = struct.unpack_from("<IIQ", payload, 0)
    if magic != _MAGIC or n != count:
        raise ValueError("malformed sparse payload")
    if kind == 0:
        return np.frombuffer(payload, dtype=np.float32, count=count,
                             offset=16).copy()
    (nnz,) = struct.unpack_from("<Q", payload, 16)
    pairs = np.frombuffer(payload, dtype=np.uint32, count=nnz * 2,
                          offset=24).reshape(nnz, 2)
    out = np.zeros(count, dtype=np.float32)
    out[pairs[:, 0]] = pairs[:, 1].view(np.float32)
    return out


def native_available() -> bool:
    return _load_native() is not None


# -- quantized delta codec (the OneBits slot) --------------------------------

_QMAGIC = 0x4651564D  # 'MVQF'
_QBITS = (1, 2, 4, 8)


def _quant_params(data: np.ndarray, bits: int):
    """(lo, step, inv_step) as float32 — min/max based so the derivation
    is order-independent (byte-identical native/numpy)."""
    lo = np.float32(data.min()) if data.size else np.float32(0.0)
    hi = np.float32(data.max()) if data.size else np.float32(0.0)
    levels = (1 << bits) - 1
    step = np.float32((hi - lo) / np.float32(levels))
    inv = np.float32(0.0) if step == 0 else np.float32(1.0) / step
    return lo, step, inv


def quant_encode(data: np.ndarray, bits: int,
                 force_numpy: bool = False) -> bytes:
    """Quantize a float32 array to ``bits`` (1|2|4|8) per value.

    Layout: <u32 magic><u32 bits><u64 count><f32 lo><f32 step> + packed
    codes (little-endian within each byte). Lossy by design — pair with
    :class:`ErrorFeedback` so the error re-enters the next delta."""
    if bits not in _QBITS:
        raise ValueError(f"quant bits must be one of {_QBITS}, got {bits}")
    data = np.ascontiguousarray(data, dtype=np.float32).reshape(-1)
    lo, step, inv = _quant_params(data, bits)
    header = struct.pack("<IIQff", _QMAGIC, bits, data.size, float(lo),
                         float(step))
    per_byte = 8 // bits
    n_bytes = -(-data.size // per_byte)
    lib = None if force_numpy else _load_native()
    if lib is not None and hasattr(lib, "MVTPU_QuantPack"):
        out = np.zeros(n_bytes, dtype=np.uint8)
        lib.MVTPU_QuantPack.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t,
            ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8)]
        lib.MVTPU_QuantPack(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), data.size,
            ctypes.c_float(float(lo)), ctypes.c_float(float(inv)), bits,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        return header + out.tobytes()
    levels = (1 << bits) - 1
    # float32 elementwise + rint (round-half-to-even): mirrors the C++
    # nearbyintf path exactly
    q = np.rint((data - lo) * inv).astype(np.float32)
    q = np.clip(q, 0, levels).astype(np.uint8)
    pad = n_bytes * per_byte - data.size
    if pad:
        q = np.concatenate([q, np.zeros(pad, np.uint8)])
    q = q.reshape(-1, per_byte)
    shifts = (np.arange(per_byte, dtype=np.uint16) * bits)
    packed = (q.astype(np.uint16) << shifts).sum(axis=1).astype(np.uint8)
    return header + packed.tobytes()


def quant_decode(payload: bytes, count: int,
                 force_numpy: bool = False) -> np.ndarray:
    """Decode a quant payload back to float32 (count values)."""
    magic, bits, n = struct.unpack_from("<IIQ", payload, 0)
    if magic != _QMAGIC or n != count or bits not in _QBITS:
        raise ValueError("malformed quant payload")
    lo, step = struct.unpack_from("<ff", payload, 16)
    lo, step = np.float32(lo), np.float32(step)
    per_byte = 8 // bits
    n_bytes = -(-count // per_byte)
    lib = None if force_numpy else _load_native()
    if lib is not None and hasattr(lib, "MVTPU_QuantUnpack"):
        out = np.zeros(count, dtype=np.float32)
        buf = np.frombuffer(payload, dtype=np.uint8, offset=24,
                            count=n_bytes)
        lib.MVTPU_QuantUnpack.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float)]
        lib.MVTPU_QuantUnpack(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), count,
            ctypes.c_float(float(lo)), ctypes.c_float(float(step)), bits,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    packed = np.frombuffer(payload, dtype=np.uint8, offset=24,
                           count=n_bytes)
    shifts = (np.arange(per_byte, dtype=np.uint16) * bits)
    mask = np.uint16((1 << bits) - 1)
    q = ((packed[:, None].astype(np.uint16) >> shifts) & mask).reshape(-1)
    q = q[:count].astype(np.float32)
    return (lo + q * step).astype(np.float32)


def quant_codes(payload: bytes, count: int):
    """Unpack a quant payload's integer codes WITHOUT dequantizing:
    ``(codes float32 (count,), lo, step, bits)``. The compressed-domain
    scoring path (multiverso_tpu/query/) folds lo/step into the score
    algebra — ``dot(q, lo + c*step) = lo*sum(q) + step*(q @ c.T)`` —
    instead of materializing ``lo + c*step`` per element. Codes come
    back as float32 (the dtype the fold multiplies in); exact, since
    every code is an integer <= 255."""
    magic, bits, n = struct.unpack_from("<IIQ", payload, 0)
    if magic != _QMAGIC or n != count or bits not in _QBITS:
        raise ValueError("malformed quant payload")
    lo, step = struct.unpack_from("<ff", payload, 16)
    per_byte = 8 // bits
    n_bytes = -(-count // per_byte)
    packed = np.frombuffer(payload, dtype=np.uint8, offset=24,
                           count=n_bytes)
    shifts = (np.arange(per_byte, dtype=np.uint16) * bits)
    mask = np.uint16((1 << bits) - 1)
    q = ((packed[:, None].astype(np.uint16) >> shifts) & mask).reshape(-1)
    return (q[:count].astype(np.float32), np.float32(lo),
            np.float32(step), int(bits))


class QuantizedDelta:
    """Marker a worker proxy hands to the wire codec: an already-encoded
    quant payload riding as one uint8 blob (tag 'quant'); the server side
    decodes back to plain float32 before process_add."""

    __slots__ = ("payload", "shape")

    def __init__(self, payload: bytes, shape) -> None:
        self.payload = payload
        self.shape = tuple(shape)


class ErrorFeedback:
    """Client-side residual accumulator for quantized pushes: each delta
    is quantized TOGETHER with the residual of all previous quantization
    errors for the touched rows, and the new error replaces it — the
    1-bit-SGD convergence recipe, generalized to 1/2/4/8 bits."""

    def __init__(self, shape, bits: int) -> None:
        self.residual = np.zeros(shape, np.float32)
        self.bits = int(bits)

    def compress(self, values: np.ndarray, ids=None) -> QuantizedDelta:
        values = np.asarray(values, np.float32)
        if ids is None:
            x = values.reshape(self.residual.shape) + self.residual
        else:
            # explicit trailing dims: reshape(0, -1) rejects empty batches
            x = (values.reshape((len(ids),) + self.residual.shape[1:])
                 + self.residual[np.asarray(ids, np.int64)])
        payload = quant_encode(x, self.bits)
        dec = quant_decode(payload, x.size).reshape(x.shape)
        if ids is None:
            self.residual = x - dec
        else:
            self.residual[np.asarray(ids, np.int64)] = x - dec
        return QuantizedDelta(payload, x.shape)
