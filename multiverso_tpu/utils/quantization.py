"""Wire sparsification (SparseFilter) — host-hop payload compression.

Reference capability (not copied): ``SparseFilter<data,index>`` encodes a
blob as (index, value) pairs when >50% zeros, with a size side-channel;
``OneBitsFilter`` was an empty stub
(``include/multiverso/util/quantization_util.h:37-161``).

TPU-era role: only host hops (C-API bridge, external clients, checkpoint
streams) benefit — on-mesh traffic is XLA collectives. The codec is the
native C++ one (``native/sparse_filter.cpp``) loaded via ctypes, with a pure
numpy fallback when the shared library isn't built. Both produce the same
byte format (magic 'MVSF').
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Optional

import numpy as np

_MAGIC = 0x4653564D  # 'MVSF'

_native: Optional[ctypes.CDLL] = None
_native_load_attempted = False


def _load_native() -> Optional[ctypes.CDLL]:
    # cache failure too: without the .so built, retrying dlopen on every
    # encode/decode would tax the hot wire-compression path
    global _native, _native_load_attempted
    if _native_load_attempted:
        return _native
    _native_load_attempted = True
    path = os.path.join(os.path.dirname(__file__), "..", "native",
                        "libmultiverso_tpu.so")
    try:
        lib = ctypes.CDLL(os.path.abspath(path))
        # size_t SparseEncodeC(const float*, size_t, uint8_t*, size_t)
        lib.MVTPU_SparseEncode.restype = ctypes.c_size_t
        lib.MVTPU_SparseEncode.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        lib.MVTPU_SparseDecode.restype = ctypes.c_int
        lib.MVTPU_SparseDecode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_float), ctypes.c_size_t]
        _native = lib
    except (OSError, AttributeError):
        _native = None
    return _native


def sparse_encode(data: np.ndarray, force_numpy: bool = False) -> bytes:
    """Encode a float32 array; sparse form when <50% nonzero."""
    data = np.ascontiguousarray(data, dtype=np.float32).reshape(-1)
    lib = None if force_numpy else _load_native()
    if lib is not None:
        # worst case: header(16) + nnz(8) + count*(4+4)
        cap = 24 + data.size * 8
        out = np.empty(cap, dtype=np.uint8)
        n = lib.MVTPU_SparseEncode(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), data.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
        return out[:n].tobytes()
    nz = np.nonzero(data)[0]
    sparse = 2 * len(nz) < data.size
    header = struct.pack("<IIQ", _MAGIC, 1 if sparse else 0, data.size)
    if not sparse:
        return header + data.tobytes()
    pairs = np.empty((len(nz), 2), dtype=np.uint32)
    pairs[:, 0] = nz.astype(np.uint32)
    pairs[:, 1] = data[nz].view(np.uint32)
    return header + struct.pack("<Q", len(nz)) + pairs.tobytes()


def sparse_decode(payload: bytes, count: int,
                  force_numpy: bool = False) -> np.ndarray:
    lib = None if force_numpy else _load_native()
    if lib is not None:
        out = np.zeros(count, dtype=np.float32)
        buf = np.frombuffer(payload, dtype=np.uint8)
        ok = lib.MVTPU_SparseDecode(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(payload),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), count)
        if not ok:
            raise ValueError("malformed sparse payload")
        return out
    magic, kind, n = struct.unpack_from("<IIQ", payload, 0)
    if magic != _MAGIC or n != count:
        raise ValueError("malformed sparse payload")
    if kind == 0:
        return np.frombuffer(payload, dtype=np.float32, count=count,
                             offset=16).copy()
    (nnz,) = struct.unpack_from("<Q", payload, 16)
    pairs = np.frombuffer(payload, dtype=np.uint32, count=nnz * 2,
                          offset=24).reshape(nnz, 2)
    out = np.zeros(count, dtype=np.float32)
    out[pairs[:, 0]] = pairs[:, 1].view(np.float32)
    return out


def native_available() -> bool:
    return _load_native() is not None
