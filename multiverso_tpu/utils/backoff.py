"""One jittered exponential-backoff helper for every retry loop.

Before this module each retry site hand-rolled its own schedule —
``fetch_layout``'s inline doubling sleep, the sharded client's dial loop,
the multihost follower's fixed 0.1 s connect poll, the standby's fixed
0.2 s resubscribe poll. Hand-rolled loops drift: some forgot jitter (a
herd of clients orphaned by one restart retries in lockstep), some
forgot the cap, none could consult a retry budget. This helper is the
single schedule they all share:

* capped exponential delay: attempt ``k`` waits ``min(cap, base*2^(k-1))``
* full jitter (uniform in ``[delay/2, delay]``), matching
  :class:`multiverso_tpu.fault.retry.RetryPolicy` so the whole stack
  desynchronizes the same way
* optional absolute deadline — ``wait()`` returns False instead of
  sleeping past it, so the caller's own failure path (raise, fatal,
  fail-all) stays in the caller
* optional cancel event — the sleep is interruptible, so a shutdown
  does not sit out a 2 s backoff
* optional retry-budget hook (:class:`multiverso_tpu.fault.retry.
  RetryBudget` or anything with ``allow() -> bool``): a denied budget
  ends the retry sequence exactly like a deadline, so a degraded peer
  sees retry pressure decay instead of storm
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional


def full_jitter(base: float, cap: float, attempt: int,
                rng: Optional[random.Random] = None) -> float:
    """Jittered delay before attempt ``attempt`` (attempt 0 -> 0.0):
    uniform in [delay/2, delay] where delay = min(cap, base*2^(k-1))."""
    if attempt <= 0:
        return 0.0
    delay = min(float(cap), float(base) * (2.0 ** (attempt - 1)))
    r = rng if rng is not None else random
    return delay * (0.5 + 0.5 * r.random())


class Backoff:
    """One retry loop's backoff state. Usage::

        bo = Backoff(base=0.05, cap=1.0, deadline=time.monotonic() + 10)
        while True:
            try:
                return attempt_the_thing()
            except OSError:
                if not bo.wait():
                    raise  # deadline passed / budget denied / cancelled

    ``deadline`` is an ABSOLUTE ``time.monotonic()`` instant (None =
    retry forever); ``wait()`` refuses to start a sleep that would end
    past it. ``budget`` is consulted BEFORE each sleep — a denial ends
    the sequence without sleeping (the deny was already counted by the
    budget). ``cancel`` (a ``threading.Event``) interrupts the sleep and
    ends the sequence when set.
    """

    def __init__(self, base: float = 0.05, cap: float = 1.0,
                 deadline: Optional[float] = None,
                 budget: Optional[object] = None,
                 cancel: Optional[threading.Event] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.base = float(base)
        self.cap = float(cap)
        self.deadline = deadline
        self.budget = budget
        self.cancel = cancel
        self._rng = rng
        self.attempt = 0

    def remaining(self) -> float:
        """Seconds until the deadline (inf when none)."""
        if self.deadline is None:
            return float("inf")
        return self.deadline - time.monotonic()

    def wait(self) -> bool:
        """Sleep the next jittered delay. False = stop retrying (the
        deadline would pass mid-sleep, the retry budget denied, or the
        cancel event fired) — nothing was slept in the deadline/budget
        cases, so the caller's error path runs promptly."""
        self.attempt += 1
        if self.budget is not None and not self.budget.allow():
            return False
        delay = full_jitter(self.base, self.cap, self.attempt, self._rng)
        if self.deadline is not None:
            left = self.deadline - time.monotonic()
            if left <= delay:
                return False
        if self.cancel is not None:
            return not self.cancel.wait(delay)
        time.sleep(delay)
        return True
