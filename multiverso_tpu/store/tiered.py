"""Hot/cold tiered row store: the beyond-RAM backend for sparse/KV
tables (docs/tiered_storage.md).

The hot tier is the same ``Dict[int, np.ndarray]`` the in-RAM servers
use; what this layer adds is a byte budget (``tier_resident_bytes``),
an on-disk cold tier (store/coldstore.py) for the tail, and the policy
that moves rows between them:

* **Demotion** — when the hot tier exceeds its budget, the oldest rows
  by last-access tick (exact LRU over a per-key logical clock) are
  written to cold segments in bounded batches and dropped. Write-ahead:
  a row leaves RAM only after its segment and the manifest are on disk.
  Runs as a ``@dispatcher_only`` maintenance step — WAL append and apply
  already happened for the triggering Add, so demotion can never reorder
  against the log.
* **Promotion** — a cold row touched by a Get is admitted back into the
  hot tier only when a TinyLFU-style frequency sketch has seen it
  ``tier_admit_touches`` times (second-chance admission): a one-shot
  full-table scan leaves the Zipf-hot working set resident instead of
  thrashing it. Adds (read-modify-write) always promote — the updated
  row is the freshest state and must live in the authoritative tier.

Telemetry: ``TIER_HOT_HITS`` / ``TIER_COLD_HITS`` / ``TIER_PROMOTIONS``
/ ``TIER_DEMOTIONS`` counters and ``TIER_RESIDENT_BYTES`` /
``TIER_COLD_BYTES`` gauges (docs/observability.md §1); cold fetch time
parks at the ``tier_cold_fetch`` wait site (§13).
"""

from __future__ import annotations

import tempfile
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import count, gauge_set
from multiverso_tpu.runtime.contracts import dispatcher_only
from multiverso_tpu.store.coldstore import ColdStore

#: Rows per demotion segment: bounds both the stall one maintenance step
#: can add to the dispatcher and the decode cost of a later cold fetch
#: (a fetch always decodes a whole segment).
DEMOTE_BATCH_ROWS = 2048

_MASK64 = (1 << 64) - 1

#: Per-process ordinal for tier spill directories: deterministic across
#: restarts (tables are re-created in the same order), so a fresh
#: incarnation lands on — and wipes — its predecessor's directory.
_TIER_SEQ = [0]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class FrequencySketch:
    """TinyLFU-flavored admission filter: two rows of 4-bit saturating
    counters under independent hash mixes, halved periodically so
    popularity decays (an aged one-shot scan cannot pollute admission
    forever). ``estimate`` is the min over the rows — an upper bound on
    the true touch count with one-sided error."""

    def __init__(self, size: int = 1 << 14) -> None:
        n = _next_pow2(max(1024, int(size)))
        self._mask = n - 1
        self._rows = np.zeros((2, n), np.uint8)
        self._touches = 0
        self._age_every = 8 * n

    def _slots(self, key: int) -> Tuple[int, int]:
        # splitmix64 finalizer: cheap, well-distributed 64-bit mix
        h = (key * 0x9E3779B97F4A7C15) & _MASK64
        h ^= h >> 30
        h = (h * 0xBF58476D1CE4E5B9) & _MASK64
        h ^= h >> 27
        h = (h * 0x94D049BB133111EB) & _MASK64
        h ^= h >> 31
        return h & self._mask, (h >> 32) & self._mask

    def touch(self, key: int) -> None:
        self._touches += 1
        if self._touches >= self._age_every:
            self._rows >>= 1
            self._touches = 0
        s0, s1 = self._slots(int(key))
        row0, row1 = self._rows
        if row0[s0] < 15:
            row0[s0] += 1
        if row1[s1] < 15:
            row1[s1] += 1

    def estimate(self, key: int) -> int:
        s0, s1 = self._slots(int(key))
        return int(min(self._rows[0][s0], self._rows[1][s1]))


def _tier_directory(explicit: Optional[str]) -> str:
    """Resolve this store's spill directory. With ``tier_dir`` set the
    directory is deterministic (``tier<ordinal>`` under the flag root,
    one root per process like ``wal_dir``) so a restarted process reuses
    and wipes its predecessor's spill; otherwise an unguessable tempdir."""
    if explicit:
        return explicit
    root = str(config.get_flag("tier_dir"))
    ordinal = _TIER_SEQ[0]
    _TIER_SEQ[0] += 1
    if root:
        import os
        path = os.path.join(root, f"tier{ordinal}")
        return path
    return tempfile.mkdtemp(prefix=f"mvtier{ordinal}_")


class TieredStore:
    """Row store with a RAM-resident hot tier and a disk cold tier.

    Single-writer by contract: every mutation happens on the serving
    dispatcher (the same discipline as the tables themselves), so there
    is no locking here. Reads that promote are mutations too — which is
    exactly why tiered tables keep routing Gets through the dispatcher.
    """

    def __init__(self, width: int, dtype, table_id: int = -1,
                 resident_bytes: Optional[int] = None,
                 cold_bits: Optional[int] = None,
                 directory: Optional[str] = None,
                 admit_touches: Optional[int] = None) -> None:
        if resident_bytes is None:
            resident_bytes = int(config.get_flag("tier_resident_bytes"))
        if cold_bits is None:
            cold_bits = int(config.get_flag("tier_cold_bits"))
        self._flag_unsub = None
        if admit_touches is None:
            admit_touches = int(config.get_flag("tier_admit_touches"))
            # flag-derived admission bar stays LIVE (watch seam): the
            # autotuner lowers it when tier_cold_fetch wait dominates.
            # An explicit constructor value stays pinned.
            self._flag_unsub = config.FLAGS.on_change(
                "tier_admit_touches", self._on_admit_change)
        self.width = int(width)
        self.dtype = np.dtype(dtype)
        self.row_bytes = self.width * self.dtype.itemsize
        self.budget = int(resident_bytes)
        if self.budget < self.row_bytes:
            log.fatal("tier_resident_bytes=%d cannot hold one %d-byte row",
                      self.budget, self.row_bytes)
        self.admit = max(1, int(admit_touches))
        # Get-path promotions enforce the budget with hysteresis: demote
        # only once resident exceeds budget+slack, so read-heavy churn
        # writes a few well-filled segments instead of one per promotion
        # (the Add path stays strict via maybe_maintain)
        self._promote_slack = max(self.row_bytes * 64, self.budget // 8)
        self._hot: Dict[int, np.ndarray] = {}
        self._tick: Dict[int, int] = {}
        self._clock = 0
        # TinyLFU sizing: counters must outnumber the items whose
        # popularity they track, i.e. the hot-tier capacity — an
        # undersized sketch collides hot keys onto shared counters and
        # admits every one-hit tail key
        self._sketch = FrequencySketch(
            size=4 * max(1024, self.budget // self.row_bytes))
        self._cold = ColdStore(_tier_directory(directory), self.width,
                               self.dtype, cold_bits, table_id)

    def _on_admit_change(self, _name: str, value) -> None:
        self.admit = max(1, int(value))

    # -- accounting ----------------------------------------------------------
    @property
    def resident_bytes(self) -> int:
        """Hot-tier payload bytes (row data only; dict/tick overhead is
        bounded per row and excluded so the budget maps to table size)."""
        return len(self._hot) * self.row_bytes

    @property
    def cold_bytes(self) -> int:
        return self._cold.total_bytes

    @property
    def hot_rows(self) -> int:
        return len(self._hot)

    @property
    def cold_rows(self) -> int:
        return len(self._cold)

    def __len__(self) -> int:
        return len(self._hot) + len(self._cold)

    def __contains__(self, key: int) -> bool:
        return key in self._hot or key in self._cold

    def stats(self) -> Dict[str, int]:
        return {"hot_rows": self.hot_rows, "cold_rows": self.cold_rows,
                "resident_bytes": self.resident_bytes,
                "cold_bytes": self.cold_bytes,
                "cold_segments": self._cold.segment_count}

    def _touch(self, key: int) -> None:
        self._clock += 1
        self._tick[key] = self._clock

    # -- serving -------------------------------------------------------------
    def get(self, key: int) -> Optional[np.ndarray]:
        """Serving read. Hot rows are returned in place; cold rows decode
        through the segment cache and are promoted only once the sketch
        has seen the key ``admit`` times."""
        row = self._hot.get(key)
        if row is not None:
            count("TIER_HOT_HITS")
            self._touch(key)
            return row
        row = self._cold.fetch(key)
        if row is None:
            return None
        # sketch only keys that exist cold: misses (insert probes, absent
        # reads) carry no admission signal, and counting them saturates
        # the sketch during bulk load, admitting every one-hit tail key
        self._sketch.touch(key)
        count("TIER_COLD_HITS")
        if self._sketch.estimate(key) >= self.admit:
            self._promote(key, row)
        return row

    def get_for_update(self, key: int) -> Optional[np.ndarray]:
        """Read-modify-write read (the Add path): always promotes, so the
        caller's in-place mutation lands in the hot tier."""
        row = self._hot.get(key)
        if row is not None:
            count("TIER_HOT_HITS")
            self._touch(key)
            return row
        row = self._cold.fetch(key)
        if row is None:
            return None
        self._sketch.touch(key)
        count("TIER_COLD_HITS")
        self._promote(key, row)
        return row

    def _promote(self, key: int, row: np.ndarray) -> None:
        self._cold.remove(key)
        self._hot[key] = row
        self._touch(key)
        count("TIER_PROMOTIONS")
        if self.resident_bytes > self.budget + self._promote_slack:
            self.maintain()

    def put(self, key: int, row: np.ndarray) -> None:
        """Insert or overwrite a row (lands hot; any cold copy is stale)."""
        if key not in self._hot:
            self._cold.remove(key)
        self._hot[key] = row
        self._touch(key)

    def items(self) -> Iterator[Tuple[int, np.ndarray]]:
        """Every (key, row), hot then cold — the snapshot/get-all path.
        No tier churn: iteration must not evict the working set."""
        yield from self._hot.items()
        yield from self._cold.items()

    def scan_blocks(self):
        """Batch scan for the query plane: the hot tier as ONE
        ``(keys, rows, None)`` block, then one block per cold segment
        from :meth:`ColdStore.scan_segments` (quantized segments arrive
        as raw codes for compressed-domain scoring). No tier churn at
        all — no sketch touches, no promotions, no fetch-cache writes —
        so a scan leaves the hit-rate exactly where it found it."""
        if self._hot:
            keys = np.fromiter(self._hot.keys(), np.int64, len(self._hot))
            keys.sort()
            rows = np.stack([self._hot[k] for k in keys.tolist()])
            yield keys, rows.astype(np.float32, copy=False), None
        yield from self._cold.scan_segments()

    # -- maintenance ---------------------------------------------------------
    def maybe_maintain(self) -> int:
        """Cheap budget probe for the hot mutation path."""
        if self.resident_bytes > self.budget:
            return self.maintain()
        return 0

    @dispatcher_only
    def maintain(self) -> int:
        """Demote least-recently-used rows until the hot tier fits the
        budget. Victims are persisted segment-by-segment and dropped only
        after each segment commits (a SIGKILL mid-step — the MV_TIER_KILL
        drill — loses nothing: hot copies still exist for any uncommitted
        batch, and recovery replays the WAL regardless)."""
        over = self.resident_bytes - self.budget
        rows_over = -(-over // self.row_bytes) if over > 0 else 0
        rows_over = min(rows_over, len(self._hot))
        if rows_over <= 0:
            self.refresh_gauges()
            return 0
        # two passes over an unmutated dict iterate in the same order
        keys_arr = np.fromiter(self._hot.keys(), np.int64, len(self._hot))
        ticks = np.fromiter((self._tick.get(k, 0) for k in self._hot.keys()),
                            np.int64, len(self._hot))
        if rows_over < len(keys_arr):
            idx = np.argpartition(ticks, rows_over - 1)[:rows_over]
        else:
            idx = np.arange(len(keys_arr))
        victims = keys_arr[idx[np.argsort(ticks[idx], kind="stable")]]
        demoted = 0
        for start in range(0, len(victims), DEMOTE_BATCH_ROWS):
            chunk = victims[start:start + DEMOTE_BATCH_ROWS]
            rows = np.stack([self._hot[k] for k in chunk.tolist()])
            self._cold.write_batch(chunk, rows)   # durable first...
            for k in chunk.tolist():              # ...then drop
                del self._hot[k]
                self._tick.pop(k, None)
            count("TIER_DEMOTIONS", len(chunk))
            demoted += len(chunk)
        self.refresh_gauges()
        return demoted

    def refresh_gauges(self) -> None:
        gauge_set("TIER_RESIDENT_BYTES", self.resident_bytes)
        gauge_set("TIER_COLD_BYTES", self.cold_bytes)

    # -- lifecycle -----------------------------------------------------------
    def clear(self) -> None:
        """Drop every row, both tiers (snapshot load repopulates)."""
        self._hot.clear()
        self._tick.clear()
        self._clock = 0
        self._cold.clear()

    def close(self) -> None:
        if self._flag_unsub is not None:
            self._flag_unsub()
            self._flag_unsub = None
        self._hot.clear()
        self._tick.clear()
        self._cold.close()
