"""Tiered beyond-RAM table storage (docs/tiered_storage.md).

``TieredStore`` keeps a table's hot rows RAM-resident under
``tier_resident_bytes`` and spills the cold tail to quantized,
CRC-framed on-disk segments (``ColdStore``); the sparse/KV server
tables plug it in behind their normal ``process_add``/``process_get``
contracts (tables/sparse_table.py, tables/kv_table.py)."""

from multiverso_tpu.store.coldstore import ColdStore
from multiverso_tpu.store.tiered import (
    DEMOTE_BATCH_ROWS, FrequencySketch, TieredStore)

__all__ = ["ColdStore", "DEMOTE_BATCH_ROWS", "FrequencySketch",
           "TieredStore"]
