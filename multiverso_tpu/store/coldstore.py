"""Cold-tier segment store: CRC-framed, quantized, atomically-committed
row spill for tiered tables (docs/tiered_storage.md).

One demotion batch becomes ONE segment file, reusing the WAL's framing
discipline (durable/wal.py)::

    segment = hdr | u32 crc32(body) | u32 body_len | body
    hdr     = "MVCS" | u8 version | i32 table_id | i64 segment
    body    = i64 count | i32 width | u8 mode | u8 dtype_len | dtype_str
              | i64 keys[count] | payload

``mode`` selects the payload codec: QUANT rides the 1/2/4/8-bit
quantization codec (utils/quantization.py, the Seide et al. 2014 packing
the wire already uses) over the concatenated float32 rows; RAW is the
verbatim ``tobytes()`` image, used when ``bits == 0``, when the table
dtype is not float32, or when a batch contains non-finite values (the
min/max grid cannot represent them). Quantized cold rows are **lossy**
(error ≤ step/2 per element); lossless tiering is ``tier_cold_bits=0``.

Why lossy is safe: the cold store is a per-incarnation **spill**, not a
durability layer. Authoritative state is snapshot + WAL (PR 2); on
restart the store wipes any leftover segments and recovery replays the
log, re-demoting whatever no longer fits. A torn or bit-flipped segment
is therefore detected by the CRC and surfaced loudly — it cannot be
"repaired" from anywhere but a restart.

Commit discipline mirrors the WAL's manifest: segment written to a tmp
name, flushed, synced, renamed into place, THEN the JSON manifest is
tmp+renamed — and only after that does the caller drop the hot copies
(write-ahead demotion). The ``MV_TIER_KILL`` chaos hook SIGKILLs the
process at either side of the commit point so CI can prove the drill:
kill -9 mid-demotion → restart → recover → zero acknowledged Adds lost.
"""

from __future__ import annotations

import json
import os
import re
import signal
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from multiverso_tpu import log
from multiverso_tpu import io as mv_io
from multiverso_tpu.obs.profiler import wait_site
from multiverso_tpu.utils.quantization import (_QBITS, quant_codes,
                                               quant_decode, quant_encode)

_SEG_MAGIC = b"MVCS"
_SEG_VERSION = 1
_SEG_HDR = struct.Struct("<4sBiq")   # magic, version, table_id, segment
_REC_HDR = struct.Struct("<II")      # crc32(body), body length
_BODY_HDR = struct.Struct("<qiBB")   # count, width, mode, dtype_len
_SEG_NAME = re.compile(r"^cseg(\d{8})\.t(-?\d+)\.mvcold$")
_MANIFEST = "TIER_MANIFEST"

MODE_RAW = 0
MODE_QUANT = 1


class ColdStore:
    """On-disk cold tier: fixed-width rows keyed by int64, batched into
    immutable segments. Not thread-safe by itself — every caller runs on
    the dispatcher (TieredStore's contract)."""

    def __init__(self, directory: str, width: int, dtype,
                 bits: int, table_id: int = -1) -> None:
        bits = int(bits)
        if bits not in (0,) + _QBITS:
            log.fatal("tier_cold_bits must be one of %s or 0 (raw), got %d",
                      _QBITS, bits)
        self.directory = directory
        self.width = int(width)
        self.dtype = np.dtype(dtype)
        self.bits = bits
        self.table_id = int(table_id)
        self._fs = mv_io.fs_for(directory)
        self._fs.makedirs(directory)
        self._index: Dict[int, int] = {}        # key -> segment id
        self._live: Dict[int, int] = {}         # segment -> live row count
        self._seg_bytes: Dict[int, int] = {}    # segment -> file bytes
        self._next_segment = 0
        self._total_bytes = 0
        # one-segment decode cache: Zipf traffic revisits the same cold
        # segment in bursts, and the fetch cost is per-segment anyway
        self._cache_seg = -1
        self._cache_rows: Dict[int, np.ndarray] = {}
        self._wipe()

    # -- lifecycle -----------------------------------------------------------
    def _wipe(self) -> None:
        """Drop every segment from a previous incarnation: the cold store
        is disposable spill — snapshot+WAL recovery rebuilds the table and
        re-demotes, so stale segments are garbage, never inputs."""
        for name in self._fs.listdir(self.directory):
            if _SEG_NAME.match(name) or name in (_MANIFEST, _MANIFEST + ".tmp"):
                try:
                    self._fs.remove(mv_io.join(self.directory, name))
                except OSError:
                    log.error("cold store: could not remove stale %s", name)

    def close(self) -> None:
        self._wipe()
        self._index.clear()
        self._live.clear()
        self._seg_bytes.clear()
        self._total_bytes = 0
        self._cache_seg = -1
        self._cache_rows = {}

    clear = close

    # -- write path (demotion) ----------------------------------------------
    def _seg_path(self, segment: int) -> str:
        return mv_io.join(self.directory,
                          f"cseg{segment:08d}.t{self.table_id}.mvcold")

    def _encode_batch(self, keys: np.ndarray, rows: np.ndarray) -> bytes:
        mode = MODE_QUANT
        if (self.bits == 0 or self.dtype != np.float32
                or not np.all(np.isfinite(rows))):
            mode = MODE_RAW
        if mode == MODE_QUANT:
            payload = quant_encode(rows.reshape(-1), self.bits)
        else:
            payload = rows.tobytes()
        dtype_str = self.dtype.str.encode("ascii")
        return (_BODY_HDR.pack(len(keys), self.width, mode, len(dtype_str))
                + dtype_str + keys.tobytes() + payload)

    def write_batch(self, keys: np.ndarray, rows: np.ndarray) -> int:
        """Persist one demotion batch as a fresh segment and commit it to
        the manifest. Returns the segment id. The caller drops its hot
        copies only AFTER this returns (write-ahead demotion)."""
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        rows = np.ascontiguousarray(rows, dtype=self.dtype)
        rows = rows.reshape(len(keys), self.width)
        body = self._encode_batch(keys, rows)
        segment = self._next_segment
        self._next_segment += 1
        path = self._seg_path(segment)
        tmp = path + ".tmp"
        with mv_io.get_stream(tmp, "w") as stream:
            stream.write(_SEG_HDR.pack(_SEG_MAGIC, _SEG_VERSION,
                                       self.table_id, segment))
            stream.write(_REC_HDR.pack(zlib.crc32(body), len(body)))
            stream.write(body)
            stream.flush()
            stream.sync()
        kill = os.environ.get("MV_TIER_KILL", "")
        if kill == "before_commit":
            os.kill(os.getpid(), signal.SIGKILL)
        self._fs.replace(tmp, path)
        # a key demoted again from a fresher hot copy supersedes its old
        # cold slot — release the stale segment references first
        for k in keys.tolist():
            old = self._index.pop(k, None)
            if old is not None:
                self._release(old)
        size = _SEG_HDR.size + _REC_HDR.size + len(body)
        for k in keys.tolist():
            self._index[k] = segment
        self._live[segment] = len(keys)
        self._seg_bytes[segment] = size
        self._total_bytes += size
        self._commit_manifest()
        if kill == "after_commit":
            os.kill(os.getpid(), signal.SIGKILL)
        return segment

    def _commit_manifest(self) -> None:
        doc = {"version": _SEG_VERSION, "table_id": self.table_id,
               "next_segment": self._next_segment, "bits": self.bits,
               "segments": sorted(self._live)}
        path = mv_io.join(self.directory, _MANIFEST)
        tmp = path + ".tmp"
        with mv_io.get_stream(tmp, "w") as stream:
            stream.write(json.dumps(doc).encode("utf-8"))
            stream.flush()
            stream.sync()
        self._fs.replace(tmp, path)

    def _release(self, segment: int) -> None:
        """One row of ``segment`` stopped being live (promoted or
        superseded); delete the file once nothing references it."""
        remaining = self._live.get(segment, 0) - 1
        if remaining > 0:
            self._live[segment] = remaining
            return
        self._live.pop(segment, None)
        self._total_bytes -= self._seg_bytes.pop(segment, 0)
        if self._cache_seg == segment:
            self._cache_seg = -1
            self._cache_rows = {}
        try:
            self._fs.remove(self._seg_path(segment))
        except OSError:
            log.error("cold store: could not remove dead segment %d",
                        segment)

    # -- read path -----------------------------------------------------------
    def _segment_body(self, segment: int):
        """Validate + parse one segment file down to its payload:
        ``(count, width, mode, dtype, keys, body, payload_offset)``."""
        path = self._seg_path(segment)
        with mv_io.get_stream(path, "r") as stream:
            data = stream.read()
        if len(data) < _SEG_HDR.size + _REC_HDR.size:
            log.fatal("cold segment %s truncated (%d bytes)", path, len(data))
        magic, version, table_id, seg = _SEG_HDR.unpack_from(data, 0)
        if magic != _SEG_MAGIC or version != _SEG_VERSION or seg != segment:
            log.fatal("cold segment %s: bad header (magic=%r seg=%d)",
                      path, magic, seg)
        crc, body_len = _REC_HDR.unpack_from(data, _SEG_HDR.size)
        body = data[_SEG_HDR.size + _REC_HDR.size:
                    _SEG_HDR.size + _REC_HDR.size + body_len]
        if len(body) != body_len or zlib.crc32(body) != crc:
            log.fatal("cold segment %s: CRC mismatch — spill corrupted; "
                      "restart to rebuild from snapshot+WAL", path)
        count, width, mode, dtype_len = _BODY_HDR.unpack_from(body, 0)
        off = _BODY_HDR.size
        dtype = np.dtype(body[off:off + dtype_len].decode("ascii"))
        off += dtype_len
        keys = np.frombuffer(body, np.int64, count, off)
        off += count * 8
        return count, width, mode, dtype, keys, body, off

    def _read_segment(self, segment: int) -> Dict[int, np.ndarray]:
        count, width, mode, dtype, keys, body, off = \
            self._segment_body(segment)
        if mode == MODE_QUANT:
            rows = quant_decode(body[off:], count * width)
        else:
            rows = np.frombuffer(body[off:], dtype, count * width)
        rows = rows.reshape(count, width)
        return {int(k): rows[i] for i, k in enumerate(keys)}

    def scan_segments(self):
        """Read-only batch scan for the query plane
        (multiverso_tpu/query/): yields one block per segment —
        ``(keys int64 (n,), rows float32 (n, width) | None, quant)``
        where ``quant`` is ``(lo, step, bits, codes float32 (n, width))``
        for quantized segments (raw integer codes, NOT dequantized — the
        caller scores in the compressed domain) and None otherwise.
        Only LIVE rows of each segment are yielded (a key superseded by
        a fresher demotion stays in the old file but not the index).
        Never touches the fetch cache or the index — the same
        no-promotion cold iteration :meth:`items` provides, batched."""
        by_segment: Dict[int, List[int]] = {}
        for key, segment in self._index.items():
            by_segment.setdefault(segment, []).append(key)
        for segment in sorted(by_segment):
            seg_keys = by_segment[segment]
            count, width, mode, dtype, keys, body, off = \
                self._segment_body(segment)
            pos = {int(k): i for i, k in enumerate(keys)}
            live_idx = np.asarray([pos[k] for k in seg_keys], np.int64)
            live = np.asarray(seg_keys, dtype=np.int64)
            if mode == MODE_QUANT:
                codes, lo, step, bits = quant_codes(body[off:],
                                                    count * width)
                codes = codes.reshape(count, width)[live_idx]
                yield live, None, (lo, step, bits, codes)
            else:
                rows = np.frombuffer(body[off:], dtype, count * width)
                rows = rows.reshape(count, width)[live_idx]
                yield live, rows.astype(np.float32, copy=False), None

    def fetch(self, key: int) -> Optional[np.ndarray]:
        """Decode the row for ``key``, or None when it is not cold. The
        returned array is a fresh copy (hot-tier mutation must not write
        through into the decode cache)."""
        segment = self._index.get(key)
        if segment is None:
            return None
        if segment != self._cache_seg:
            with wait_site("tier_cold_fetch"):
                self._cache_rows = self._read_segment(segment)
                self._cache_seg = segment
        return self._cache_rows[key].astype(self.dtype, copy=True)

    def remove(self, key: int) -> None:
        """Forget ``key`` (promoted back hot, or deleted)."""
        segment = self._index.pop(key, None)
        if segment is not None:
            self._release(segment)

    def items(self) -> Iterator[Tuple[int, np.ndarray]]:
        """Iterate every cold (key, row) — snapshot/get-all path; decodes
        segment-at-a-time without disturbing the fetch cache."""
        by_segment: Dict[int, List[int]] = {}
        for key, segment in self._index.items():
            by_segment.setdefault(segment, []).append(key)
        for segment, seg_keys in by_segment.items():
            rows = self._read_segment(segment)
            for key in seg_keys:
                yield key, rows[key].astype(self.dtype, copy=True)

    def keys(self):
        return self._index.keys()

    def __contains__(self, key: int) -> bool:
        return key in self._index

    def __len__(self) -> int:
        return len(self._index)

    @property
    def total_bytes(self) -> int:
        return self._total_bytes

    @property
    def segment_count(self) -> int:
        return len(self._live)
