"""Server-side optimizers ("updaters") applied inside ProcessAdd.

Reference capability (not copied): ``Updater<T>::Update/Access`` + factory
``GetUpdater`` keyed on the ``updater_type`` flag, with ``AddOption``/
``GetOption`` per-request hyperparameter envelopes riding each message
(``include/multiverso/updater/updater.h:10-132``, ``src/updater/updater.cpp``);
concrete updaters: default (+=), SGD (-=), momentum EMA, per-worker AdaGrad
(``include/multiverso/updater/{sgd,momentum,adagrad}_updater.h``), and a
declared-but-absent DCASGD slot (``CMakeLists.txt:9``).

TPU-native re-design: an updater is a *pure function* ``apply(data, states,
delta, option) -> (data, states)`` over same-shape slices, jitted and donated
by the owning table, so the whole-table and row-subset paths share one
compiled update. Optimizer state lives in HBM sharded exactly like the table.
Every state array carries a leading worker dimension (1 when the optimizer is
worker-agnostic) so per-worker state (AdaGrad, DCASGD) and shared state
(momentum) flow through the same table machinery. Known reference bug NOT
reproduced: AdaGrad accumulator was read via a copy and never persisted
(``adagrad_updater.h:26``); here states round-trip through the jitted call.

DCASGD is fully implemented (the reference only reserved the option): the
delay-compensated ASGD rule ``data -= lr*(g + lambda * g*g*(data - backup))``
with a per-worker backup of parameters at last read.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from multiverso_tpu import config, log


@dataclass
class AddOption:
    """Per-request hyperparameters riding an Add (wire-compatible 5-field
    envelope: worker_id, momentum, learning_rate, rho, lambda)."""

    worker_id: int = 0
    momentum: float = 0.0
    learning_rate: float = 0.1
    rho: float = 0.1
    lambda_: float = 1.0

    _WIRE = struct.Struct("<i4f")

    def to_bytes(self) -> bytes:
        return self._WIRE.pack(self.worker_id, self.momentum,
                               self.learning_rate, self.rho, self.lambda_)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "AddOption":
        w, m, lr, rho, lam = cls._WIRE.unpack(raw[:cls._WIRE.size])
        return cls(w, m, lr, rho, lam)

    def scalars(self) -> Tuple[float, float, float, float]:
        return (self.momentum, self.learning_rate, self.rho, self.lambda_)


@dataclass
class GetOption:
    worker_id: int = 0

    _WIRE = struct.Struct("<i")

    def to_bytes(self) -> bytes:
        return self._WIRE.pack(self.worker_id)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "GetOption":
        (w,) = cls._WIRE.unpack(raw[:cls._WIRE.size])
        return cls(w)


class Updater:
    """Base updater. Subclasses override ``apply`` (and ``state_spec`` when
    they carry optimizer state).

    ``data``: slice of table values (any shape). ``states``: dict of state
    slices, each shaped like ``data`` (already sliced to the acting worker).
    ``option_scalars``: (momentum, lr, rho, lambda) as traced scalars.
    """

    name = "default"
    per_worker_state = False

    def state_spec(self, table_shape: Tuple[int, ...],
                   dtype: Any) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
        """name -> (shape-suffix, dtype); actual arrays get a leading worker dim."""
        return {}

    def apply(self, data, states: Dict[str, Any], delta,
              option_scalars) -> Tuple[Any, Dict[str, Any]]:
        return data + delta, states

    def access(self, data):
        """Transform on Get (reference ``Updater::Access``); default identity."""
        return data


class SGDUpdater(Updater):
    """``data -= delta`` — delta pre-scaled by the caller."""

    name = "sgd"

    def apply(self, data, states, delta, option_scalars):
        return data - delta, states


class MomentumUpdater(Updater):
    """EMA smoothing: ``smooth = m*smooth + (1-m)*delta; data -= smooth``."""

    name = "momentum_sgd"

    def state_spec(self, table_shape, dtype):
        return {"smooth": (table_shape, dtype)}

    def apply(self, data, states, delta, option_scalars):
        m = option_scalars[0]
        smooth = m * states["smooth"] + (1.0 - m) * delta
        return data - smooth, {"smooth": smooth}


class AdaGradUpdater(Updater):
    """Per-worker historic squared-gradient accumulators:
    ``g_sqr += delta²; data -= lr * delta / sqrt(g_sqr + rho)``."""

    name = "adagrad"
    per_worker_state = True

    def state_spec(self, table_shape, dtype):
        return {"g_sqr": (table_shape, jnp.float32)}

    def apply(self, data, states, delta, option_scalars):
        lr, rho = option_scalars[1], option_scalars[2]
        g_sqr = states["g_sqr"] + jnp.square(delta).astype(jnp.float32)
        step = lr * delta / jnp.sqrt(g_sqr + rho).astype(delta.dtype)
        return data - step, {"g_sqr": g_sqr}


class DCASGDUpdater(Updater):
    """Delay-compensated ASGD: compensates gradient staleness with the
    diagonal Hessian approximation ``g ⊙ g ⊙ (data - backup)`` where
    ``backup`` is the per-worker parameter snapshot at last Get."""

    name = "dcasgd"
    per_worker_state = True

    def state_spec(self, table_shape, dtype):
        return {"backup": (table_shape, dtype)}

    def apply(self, data, states, delta, option_scalars):
        lr, lam = option_scalars[1], option_scalars[3]
        backup = states["backup"]
        comp = delta + lam * delta * delta * (data - backup)
        new_data = data - lr * comp
        return new_data, {"backup": new_data}


_REGISTRY: Dict[str, Callable[[], Updater]] = {
    "default": Updater,
    "sgd": SGDUpdater,
    "momentum_sgd": MomentumUpdater,
    "adagrad": AdaGradUpdater,
    "dcasgd": DCASGDUpdater,
}


def register_updater(name: str, factory: Callable[[], Updater]) -> None:
    """Open extension point (the reference's factory was a closed switch)."""
    _REGISTRY[name] = factory


def get_updater(dtype: Any, updater_type: str = "") -> Updater:
    """Factory keyed on the ``updater_type`` flag. Integer tables always get
    the plain accumulating updater (reference behavior)."""
    if np.issubdtype(np.dtype(dtype), np.integer):
        return Updater()
    name = updater_type or config.get_flag("updater_type")
    factory = _REGISTRY.get(name)
    if factory is None:
        log.fatal("unknown updater_type: %s", name)
    return factory()
