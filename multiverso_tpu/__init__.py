"""multiverso_tpu — a TPU-native parameter-server framework.

Capability-parity rebuild of the Multiverso parameter-server framework
(reference: ``include/multiverso/multiverso.h``, ``src/multiverso.cpp``,
``binding/python/multiverso/api.py``) re-founded on JAX/XLA: table shards are
``jax.Array``s in HBM over a device mesh, Get/Add are jitted gathers and
donated scatter-updates, server-side optimizers are pure jitted functions,
and the allreduce path is ``psum``/host-collectives instead of MPI.

Public surface (MV_* parity):

    init / shutdown / barrier
    rank / size / num_workers / num_servers / worker_id / server_id
    worker_id_to_rank / server_id_to_rank / is_master_worker
    set_flag / parse_cmd_flags
    aggregate                      (MV_Aggregate: in-place-sum allreduce)
    query                          (server-side top-k retrieval pushdown)
    ArrayTable / MatrixTable / KVTable handles (create_table factory)
    worker(slot)                   (bind a logical worker context to a thread)
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterator, Optional, Sequence

import numpy as np

from multiverso_tpu import config as _config
from multiverso_tpu import log  # noqa: F401  (re-export)
from multiverso_tpu.config import get_flag, parse_cmd_flags, set_flag  # noqa: F401
from multiverso_tpu.dashboard import Dashboard, Timer, monitor  # noqa: F401
from multiverso_tpu.runtime.node import Role  # noqa: F401
from multiverso_tpu.runtime.programs import (  # noqa: F401
    register_program, registered_programs)
from multiverso_tpu.runtime.zoo import Zoo

__version__ = "0.1.0"


# -- lifecycle (MV_Init / MV_ShutDown / MV_Barrier) -------------------------

def init(argv: Optional[Sequence[str]] = None, sync: Optional[bool] = None,
         **flag_overrides: Any) -> list:
    """Bring up the runtime. ``argv`` accepts ``-key=value`` tokens (CLI
    parity); keyword overrides hit the same flag registry
    (e.g. ``init(sync=True, local_workers=4)``)."""
    if sync is not None:
        set_flag("sync", sync)
    for key, value in flag_overrides.items():
        set_flag(key, value)
    remaining = Zoo.instance().start(argv)
    _configure_native_allocator()
    _configure_profiling()
    _start_metrics_logger()
    _start_observability()
    _start_autotune()
    return remaining


_metrics_logger = None


def _start_metrics_logger() -> None:
    """Start the periodic JSONL snapshot thread when the ``metrics_path``
    flag is set (obs/logger.py); idempotent across repeated init()."""
    global _metrics_logger
    path = str(get_flag("metrics_path"))
    if not path or _metrics_logger is not None:
        return
    from multiverso_tpu.obs.logger import MetricsLogger
    _metrics_logger = MetricsLogger(
        path, float(get_flag("metrics_interval_seconds")))


def _stop_metrics_logger() -> None:
    global _metrics_logger
    if _metrics_logger is not None:
        _metrics_logger.close()  # flushes a final snapshot
        _metrics_logger = None


_slo_engine = None


def _start_observability() -> None:
    """Start the observability plane's background halves: the
    time-series sampler (``timeseries_interval_seconds``; <= 0 disables)
    and — only when ``slo_spec`` declares objectives — the SLO burn-rate
    engine (obs/slo.py). Idempotent across repeated init()."""
    global _slo_engine
    if float(get_flag("timeseries_interval_seconds")) > 0:
        from multiverso_tpu.obs.timeseries import TIMESERIES
        TIMESERIES.start()
    if bool(get_flag("profile_continuous")):
        from multiverso_tpu.obs.profiler import PROFILER
        PROFILER.hz = max(float(get_flag("profile_hz")), 1e-3)
        PROFILER.max_frames = int(get_flag("profile_max_frames"))
        PROFILER.emit_metrics = True
        PROFILER.start()
    if str(get_flag("slo_spec")).strip() and _slo_engine is None:
        from multiverso_tpu.obs.slo import SLOEngine
        _slo_engine = SLOEngine()
        _slo_engine.start()


def _stop_observability() -> None:
    global _slo_engine
    from multiverso_tpu.obs.timeseries import TIMESERIES
    TIMESERIES.stop()
    from multiverso_tpu.obs.profiler import PROFILER
    PROFILER.stop()
    if _slo_engine is not None:
        _slo_engine.stop()
        _slo_engine = None


_autotuner = None


def _start_autotune() -> None:
    """Start the self-tuning KnobController (tune/) when the
    ``autotune`` flag is set; idempotent across repeated init(). With
    the flag off NOTHING is built — no thread, no TUNE_* metrics, the
    runtime stays bit-identical to an untuned build."""
    global _autotuner
    if not bool(get_flag("autotune")) or _autotuner is not None:
        return
    from multiverso_tpu.tune import KnobController
    _autotuner = KnobController()
    if _autotuner.interval > 0:
        _autotuner.start()


def _stop_autotune() -> None:
    global _autotuner
    if _autotuner is not None:
        _autotuner.stop()
        _autotuner = None


def autotune():
    """The flag-started self-tuning controller
    (:class:`~multiverso_tpu.tune.KnobController`) — None unless
    ``autotune`` was set at init. Tests and drills may also build their
    own ``KnobController`` directly and drive ``tick_now()``."""
    return _autotuner


def slo_engine():
    """The flag-started SLO engine (None unless ``slo_spec`` was set at
    init); tests and dashboards may also build their own
    :class:`~multiverso_tpu.obs.slo.SLOEngine` directly."""
    return _slo_engine


def profiler():
    """The process-wide sampling profiler
    (:data:`~multiverso_tpu.obs.profiler.PROFILER`) — running when
    ``profile_continuous`` was set at init, otherwise idle but usable
    directly (``mv.profiler().start()`` / ``.sample_once()``)."""
    from multiverso_tpu.obs.profiler import PROFILER
    return PROFILER


def _configure_profiling() -> None:
    """Wire the tracing flags (SURVEY §5's 'host timers plus optional
    trace annotations'): ``profile_annotations`` makes every
    ``dashboard.monitor`` section a ``jax.profiler.TraceAnnotation`` so
    dispatcher device time (SERVER_PROCESS_*) is visible in real traces;
    ``trace_dir`` additionally starts a profiler trace for the whole
    init→shutdown span."""
    trace_dir = str(get_flag("trace_dir"))
    Dashboard.profile_annotations = bool(
        get_flag("profile_annotations")) or bool(trace_dir)
    if trace_dir:
        import jax
        jax.profiler.start_trace(trace_dir)


def _stop_profiling() -> None:
    if str(get_flag("trace_dir")):
        import jax
        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass  # trace already stopped (repeated shutdown)


def _configure_native_allocator() -> None:
    """Plumb the ``allocator_type`` / ``allocator_alignment`` flags into the
    native host pool (reference: the flags were read at allocator
    construction, src/util/allocator.cpp:10,153). Too-late configuration
    (something already allocated) is reported, not fatal."""
    import ctypes
    from multiverso_tpu.utils.quantization import _load_native
    lib = _load_native()
    if lib is None or not hasattr(lib, "MVTPU_ConfigureAllocator"):
        return  # native lib absent or predates the configure export
    lib.MVTPU_ConfigureAllocator.restype = ctypes.c_int
    lib.MVTPU_ConfigureAllocator.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    kind = str(get_flag("allocator_type"))
    rc = lib.MVTPU_ConfigureAllocator(
        kind.encode(), int(get_flag("allocator_alignment")))
    if rc == -1:
        log.info("native allocator already instantiated; allocator_type=%s "
                 "ignored for this process", kind)
    elif rc == -2:
        log.error("unknown allocator_type %r (want smart|default)", kind)
    elif rc == -3:
        log.error("allocator_alignment=%s is not a power of two >= %d; "
                  "keeping the previous alignment",
                  get_flag("allocator_alignment"), 8)


def shutdown(finalize_net: bool = True) -> None:
    _stop_autotune()
    Zoo.instance().stop(finalize_net)
    _stop_profiling()
    _stop_metrics_logger()
    _stop_observability()


def barrier() -> None:
    Zoo.instance().barrier()


def process_barrier() -> None:
    """Cross-process rendezvous: real under a multi-process (multihost)
    mesh, a no-op single-process."""
    Zoo.instance().process_barrier()


# -- identity ---------------------------------------------------------------

def rank() -> int:
    return Zoo.instance().rank


def size() -> int:
    return Zoo.instance().size


def num_workers() -> int:
    return Zoo.instance().num_workers


def workers_num() -> int:  # python-binding spelling
    return num_workers()


def num_servers() -> int:
    return Zoo.instance().num_servers


def server_num() -> int:  # python-binding spelling
    return num_servers()


def worker_id() -> int:
    return Zoo.instance().current_worker_id()


def server_id() -> int:
    return Zoo.instance().node.server_id


def worker_id_to_rank(wid: int) -> int:
    return Zoo.instance().worker_id_to_rank(wid)


def server_id_to_rank(sid: int) -> int:
    return Zoo.instance().server_id_to_rank(sid)


def is_master_worker() -> bool:
    """Worker 0 seeds shared state (python-binding contract)."""
    return worker_id() == 0


@contextlib.contextmanager
def worker(local_slot: int) -> Iterator[int]:
    """Bind the calling thread to logical worker context ``local_slot``."""
    zoo = Zoo.instance()
    zoo.bind_worker(local_slot)
    try:
        yield zoo.rank * zoo.local_workers + local_slot
    finally:
        zoo.bind_worker(0)


# -- collectives (MV_Aggregate) ---------------------------------------------

def aggregate(data: Any) -> Any:
    """Elementwise sum of ``data`` across every worker; every caller gets
    the summed result (in-place-sum semantics of ``MV_Aggregate``).

    Host inputs (numpy arrays, or lists of them — a model's leaves) sum
    on the host and return copies. DEVICE inputs (``jax.Array`` or a
    list of them) reduce as ONE jitted tree-sum in HBM and the result
    stays on device — the MA-mode fast path; mixing host and device
    values across workers in one round is rejected."""
    return Zoo.instance().aggregate(data)


# Bind the retrieval subpackage NOW so the front door below wins the
# `query` name on this module: once multiverso_tpu.query sits in
# sys.modules, later imports of it (or its engine) are cache hits and
# never re-assign the parent attribute over the function.
from multiverso_tpu import query as _query_plane  # noqa: E402,F401


def query(table: Any, vecs: Any, k: int, metric: str = "dot"):
    """Server-side top-k retrieval pushdown over ``table`` (query/):
    score every row against the query matrix ``vecs`` ((n_q, dim)
    float32) under ``metric`` (``dot`` | ``cosine``) and return
    ``(ids, scores)`` — each (n_q, k') with k' = min(k, rows), ranked
    score-descending, ties toward the lower global id. Works on any
    worker-table handle — local, remote, or sharded (the shard router
    merges per-shard partial top-ks into the identical global answer).
    Slot-free and replica-servable: results may trail the primary by
    the read tier's staleness budget (docs/serving.md)."""
    return table.query(vecs, k, metric=metric)


# -- remote table serving (cross-process PS) ---------------------------------
# The reference's core product: workers in OTHER processes reach tables over
# the network (worker actor → communicator → net → server). Here the
# mesh-owning process calls serve(); off-mesh clients call remote_connect()
# and get worker-table proxies with identical get/add semantics.

def serve(endpoint: str = "127.0.0.1:0") -> str:
    """Start serving this process's tables to remote clients; returns the
    dialable endpoint (pass port 0 for ephemeral). Set the
    ``remote_workers`` flag at init so BSP clocks and per-worker updater
    state cover the remote clients.

    With the ``wal_dir`` flag set, serving is durable: every remote Add is
    write-ahead-logged before its ACK, and any dedup seeds left by
    ``durable_recover()`` (or a standby's replication tail) repopulate the
    idempotent-replay window so exactly-once holds across the restart."""
    zoo = Zoo.instance()
    if not zoo.started or zoo.server is None:
        log.fatal("serve: init() the PS runtime first (not available in ma mode)")
    if not str(get_flag("metrics_role")):
        # fleet identity for labeled Prometheus exposition; replicas and
        # standbys stamp their own role when they start serving
        set_flag("metrics_role", "primary")
    if zoo.remote_server is None:
        wal_dir = str(get_flag("wal_dir"))
        if wal_dir and zoo.server.wal is None:
            from multiverso_tpu.durable.wal import WalWriter
            zoo.server.wal = WalWriter(wal_dir)
        from multiverso_tpu.runtime.remote import RemoteServer
        zoo.remote_server = RemoteServer(zoo)
        if zoo._dedup_seeds:
            zoo.remote_server.seed_dedup(zoo._dedup_seeds)
            zoo._dedup_seeds = None
        try:
            return zoo.remote_server.serve(endpoint)
        except OSError:
            # bind failed (port still held): leave no half-serving state
            # behind so a retry — the standby's failover loop — can call
            # serve() again
            zoo.remote_server.stop()
            zoo.remote_server = None
            raise
    return zoo.remote_server.endpoint


def remote_connect(endpoint: str, timeout: float = 30.0,
                   read_endpoints: Optional[Sequence[str]] = None,
                   read_preference: Optional[str] = None):
    """Connect to a serving process; returns a RemoteClient whose
    ``.table(table_id)`` / ``.tables()`` give worker-table proxies.

    ``read_endpoints`` (serving read replicas of this primary, see
    ``mv.warm_standby(...).serve_reads()``) plus a non-primary
    ``read_preference`` (replica|hedged; default: the ``read_preference``
    flag) route Gets through the read tier — bounded-staleness client
    cache, budget-admitted replicas, transparent primary fallback
    (docs/serving.md)."""
    from multiverso_tpu.runtime.remote import RemoteClient
    return RemoteClient(endpoint, timeout=timeout,
                        read_endpoints=(list(read_endpoints)
                                        if read_endpoints else None),
                        read_preference=read_preference)


def stats(endpoint: str, timeout: float = 10.0):
    """Live stats RPC: pull a (possibly remote) serving process's full
    dashboard — monitors, counters, gauges, and latency histograms with
    caller-side p50/p95/p99 — without taking a worker slot. Returns a
    :class:`~multiverso_tpu.obs.metrics.StatsSnapshot`; metric catalog in
    ``docs/observability.md``. Works against primaries AND serving read
    replicas (their read listener answers the same probe)."""
    from multiverso_tpu.runtime.remote import fetch_stats
    return fetch_stats(endpoint, timeout=timeout)


def watermark(endpoint: str, timeout: float = 10.0):
    """Watermark probe (read-replica tier): ``{"role", "watermark",
    "primary_watermark", "lag"}`` for any serving endpoint — a primary
    reports its WAL append sequence, a read replica its replay sequence
    and how many records it trails its primary by. Slot-free, like
    ``mv.stats`` (docs/serving.md)."""
    from multiverso_tpu.runtime.remote import fetch_watermark
    return fetch_watermark(endpoint, timeout=timeout)


# -- sharded serving tier (multiverso_tpu/shard/, docs/sharding.md) ----------
# The reference's horizontal-scaling story: tables range/hash-sharded across
# server ranks, clients splitting requests and merging partial replies. Here
# a ShardGroup launches one serving process per shard (own WAL, leases,
# optional warm standby) and clients route through a ShardedClient.

def serve_sharded(tables: Sequence[dict], shards: Optional[int] = None,
                  **kwargs: Any):
    """Launch a shard group serving ``tables`` (declarative specs, e.g.
    ``[{"kind": "matrix", "num_row": 1 << 20, "num_col": 64}]``) across
    ``shards`` serving processes (default: the ``shards`` flag). Each
    shard owns its slice of every table, its own lease table and dedup
    window, its own WAL dir (``durable=True``), and optionally a warm
    standby (``standby=True``). Returns the started
    :class:`~multiverso_tpu.shard.group.ShardGroup` — use ``.connect()``
    for a routing client, ``.endpoints``/``.layout`` for bootstrap info,
    ``.stop()`` to tear down. Does NOT need ``mv.init`` in the calling
    process (the shard children own their runtimes)."""
    from multiverso_tpu.shard.group import ShardGroup
    return ShardGroup(tables, shards=shards, **kwargs).start()


def reshard(group):
    """An elastic-membership coordinator for a live, durable shard group:
    ``mv.reshard(group).split(k)`` / ``.merge(k)`` / ``.move(k)`` migrate
    key ranges under traffic with zero acknowledged-Add loss — fresh
    joiner processes catch up over the donors' WAL streams, donors fence
    at a watermark cutover, and clients re-route in flight
    (:mod:`multiverso_tpu.shard.reshard`, docs/sharding.md §live
    migration)."""
    from multiverso_tpu.shard.reshard import MigrationCoordinator
    return MigrationCoordinator(group)


def shard_connect(endpoints: Any = None, timeout: float = 30.0):
    """Connect to an existing shard group: fetch the layout manifest from
    the first reachable member (``Control_Layout`` RPC), then build a
    :class:`~multiverso_tpu.shard.router.ShardedClient` whose
    ``.table(table_id)`` proxies split Get/Add across the shards and
    merge the partial replies bit-identically to a single-server run.
    ``endpoints``: a host:port string, a list of them, or None to read
    the ``shard_endpoints`` flag (validated fail-fast)."""
    from multiverso_tpu.shard.partition import parse_shard_endpoints
    from multiverso_tpu.shard.router import ShardedClient, fetch_layout
    if endpoints is None:
        endpoints = get_flag("shard_endpoints")
    candidates = parse_shard_endpoints(endpoints)
    errors = []
    for endpoint in candidates:
        try:
            layout = fetch_layout(endpoint, timeout=timeout)
            return ShardedClient(layout, timeout=timeout)
        except (OSError, TimeoutError, ConnectionError, RuntimeError) as exc:
            errors.append(f"{endpoint}: {exc!r}")
    log.fatal("shard_connect: no member answered the layout RPC (%s)",
              "; ".join(errors))


def stats_all(endpoints: Any, timeout: Optional[float] = None,
              replicas: Optional[Sequence[Sequence[str]]] = None):
    """Fan ``mv.stats`` across a shard group and merge: counters summed,
    histograms merged by bucket addition (quantiles compute on the union
    of the members' exact counts), with per-shard sub-views kept on
    ``.shards``. ``endpoints``: list of host:port, a comma-separated
    string, or a :class:`~multiverso_tpu.shard.group.ShardGroup` (whose
    read-replica fleets are probed automatically). ``replicas`` — one
    endpoint list per shard — adds per-replica sub-views on
    ``.replicas`` (a dict ``endpoint -> StatsSnapshot``), merged into
    the totals alongside the primaries (replica replay-lag gauges
    REPLICA_WATERMARK / REPLICA_LAG_RECORDS live there).

    Probes run CONCURRENTLY with a per-endpoint timeout (default: the
    ``stats_timeout_seconds`` flag) and the merge is PARTIAL: members
    that do not answer are listed on the result's ``.unreachable``
    instead of failing the whole fan-out — one dead replica must not
    blind the operator to the rest of the fleet. Raises only when NO
    member answered."""
    import threading as _threading
    from multiverso_tpu.obs.metrics import merge_stats
    from multiverso_tpu.shard.partition import parse_shard_endpoints
    if timeout is None:
        timeout = float(get_flag("stats_timeout_seconds"))
    if replicas is None:
        replicas = getattr(endpoints, "replica_endpoints", None)
    endpoints = getattr(endpoints, "endpoints", endpoints)
    primary_eps = list(parse_shard_endpoints(endpoints))
    replica_eps = [str(e) for fleet in (replicas or []) for e in fleet]
    results: dict = {}
    lock = _threading.Lock()

    def probe(ep: str) -> None:
        try:
            snap = stats(ep, timeout=timeout)
        except (OSError, RuntimeError):
            snap = None
        with lock:
            results[ep] = snap

    all_eps = primary_eps + [e for e in replica_eps
                             if e not in primary_eps]
    threads = [_threading.Thread(target=probe, args=(ep,), daemon=True,
                                 name="mv-stats-probe")
               for ep in all_eps]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 1.0)
    snaps = [results[e] for e in primary_eps
             if results.get(e) is not None]
    replica_snaps = {e: results[e] for e in replica_eps
                     if results.get(e) is not None}
    unreachable = [e for e in all_eps if results.get(e) is None]
    if not snaps and not replica_snaps:
        raise ConnectionError(
            f"stats_all: no endpoint answered within {timeout:.1f}s "
            f"({', '.join(all_eps)})")
    merged = merge_stats(snaps + list(replica_snaps.values()))
    merged.shards = snaps  # primaries only; replicas get their own view
    merged.replicas = replica_snaps
    merged.unreachable = unreachable
    return merged


def traces(endpoints: Any, timeout: Optional[float] = None,
           req_id: Optional[int] = None):
    """Pull and stitch cross-process traces: one slot-free
    ``Control_Traces`` probe per endpoint plus this process's own trace
    store, clock-corrected and merged into causally-ordered
    :class:`~multiverso_tpu.obs.collector.StitchedTrace` spans
    (docs/observability.md). ``endpoints``: a list of host:port, a
    :class:`~multiverso_tpu.shard.group.ShardGroup`, or a
    :class:`~multiverso_tpu.shard.router.ShardedClient` layout —
    replica fleets are included automatically. Returns the stitched
    spans (all, or just ``req_id``'s), oldest first."""
    from multiverso_tpu.obs.collector import TraceCollector
    eps = _fleet_endpoints(endpoints)
    collector = TraceCollector(eps, timeout=timeout)
    collector.collect()
    return collector.stitch(req_id)


def attribution(endpoints: Any, timeout: Optional[float] = None,
                quantile: Optional[float] = None,
                include_profiles: bool = True):
    """Fleet latency attribution (``mv.attribution``): pull + stitch the
    fleet's traces, decompose every span into named critical-path
    segments (in-process stage gaps and ``wire:`` boundary crossings),
    and aggregate them into an
    :class:`~multiverso_tpu.obs.critpath.AttributionReport` — the
    "p99 Get: 61% replica apply-lag wait, 22% wire" table. ``quantile``
    (e.g. ``0.99``) restricts aggregation to the slowest tail;
    ``include_profiles`` annotates the report with each process's
    sampling profile over the slot-free ``Control_Profile`` RPC."""
    from multiverso_tpu.obs.critpath import fleet_attribution
    return fleet_attribution(_fleet_endpoints(endpoints), timeout=timeout,
                             quantile=quantile,
                             include_profiles=include_profiles)


def chargeback(endpoints: Any, timeout: Optional[float] = None,
               quantile: Optional[float] = None):
    """Fleet cost attribution BY TENANT (``mv.chargeback``): pull +
    stitch the fleet's tenant-tagged traces and partition the same
    critical-path segments :func:`attribution` decomposes into a
    per-tenant table — share-of-fleet-time (sums to ~1.0), apply+WAL
    time, p99, bytes pushed, Adds admitted vs shed — the "which tenant
    bought which fraction of the machine" answer
    (docs/observability.md §Chargeback). Returns a
    :class:`~multiverso_tpu.obs.chargeback.ChargebackReport`; call
    ``.display()`` to print it."""
    from multiverso_tpu.obs.chargeback import fleet_chargeback
    return fleet_chargeback(_fleet_endpoints(endpoints), timeout=timeout,
                            quantile=quantile)


def top(endpoints: Any, timeout: Optional[float] = None,
        format: str = "text") -> str:
    """The live fleet view (``mv.top``): one stats+watermark probe per
    serving endpoint, rendered as a terminal table (or ``format="html"``
    for a browser tab) of per-shard/per-replica roles, watermarks, lag,
    served request counts, Get p99 and burn-alert state, plus the local
    SLO engine's panel when one is running (obs/slo.py)."""
    from multiverso_tpu.obs.slo import fleet_top
    return fleet_top(_fleet_endpoints(endpoints), engine=_slo_engine,
                     timeout=timeout, format=format)


def _fleet_endpoints(endpoints: Any) -> list:
    """Flatten a fleet handle — ShardGroup, layout manifest dict, list,
    or comma-string — into the full serving-endpoint list (primaries
    first, then replica fleets), deduplicated in order."""
    from multiverso_tpu.shard.partition import parse_shard_endpoints
    replicas = getattr(endpoints, "replica_endpoints", None)
    if isinstance(endpoints, dict):  # a layout manifest
        replicas = list((endpoints.get("replicas") or {}).values())
        endpoints = endpoints.get("endpoints", [])
    eps = list(parse_shard_endpoints(
        getattr(endpoints, "endpoints", endpoints)))
    for fleet in (replicas or []):
        eps.extend(str(e) for e in fleet)
    seen: dict = {}
    for e in eps:
        seen.setdefault(e)
    return list(seen)


def stop_serving() -> None:
    """Stop the remote table server while keeping the runtime up. A later
    ``serve()`` binds fresh — the server-restart recovery path: restart,
    ``checkpoint.restore_tables(...)`` (or ``durable_recover()``),
    ``serve()`` on the old endpoint, and reconnecting clients resume (see
    docs/fault_tolerance.md)."""
    zoo = Zoo.instance()
    if zoo.remote_server is not None:
        zoo.remote_server.stop()
        zoo.remote_server = None
    if zoo.server is not None and zoo.server.wal is not None:
        zoo.server.wal.close()
        zoo.server.wal = None


def durable_recover(tables: Optional[Sequence[Any]] = None,
                    directory: Optional[str] = None):
    """Exactly-once restart recovery (docs/fault_tolerance.md §7): load
    the manifest snapshot, replay the WAL — truncating any torn tail —
    and stage the replayed req-ids so the next ``serve()`` rebuilds its
    dedup window. Call after ``create_table`` (same order as before the
    crash) and BEFORE ``serve()``. Returns the
    :class:`~multiverso_tpu.durable.wal.RecoveryResult`."""
    from multiverso_tpu.durable.wal import recover
    zoo = Zoo.instance()
    directory = directory or str(get_flag("wal_dir"))
    if not directory:
        log.fatal("durable_recover: pass a directory or set the wal_dir "
                  "flag")
    source = list(tables) if tables is not None else list(zoo._worker_tables)
    result = recover(source, directory)
    zoo._dedup_seeds = result.seeds
    return result


def wal_writer():
    """The serving process's WAL writer (None until ``serve()`` runs with
    the ``wal_dir`` flag set) — pass it to ``CheckpointDriver(...,
    wal=mv.wal_writer())`` so snapshots compact the log."""
    zoo = Zoo.instance()
    return zoo.server.wal if zoo.server is not None else None


def warm_standby(primary_endpoint: str, service_endpoint: str,
                 tables: Optional[Sequence[Any]] = None,
                 lease_seconds: Optional[float] = None,
                 takeover: bool = True):
    """Start a warm standby tailing ``primary_endpoint``'s WAL; on primary
    lease expiry it binds ``service_endpoint`` and clients fail over
    transparently (durable/standby.py). Returns the started
    :class:`~multiverso_tpu.durable.standby.WarmStandby` — call
    ``.serve_reads()`` on it to promote it into a serving read replica
    (watermark-stamped slot-free Gets, docs/serving.md).
    ``takeover=False`` builds a pure read replica: several can tail one
    primary without racing to bind its endpoint when it dies."""
    from multiverso_tpu.durable.standby import WarmStandby
    return WarmStandby(primary_endpoint, service_endpoint, tables=tables,
                       lease_seconds=lease_seconds,
                       takeover=takeover).start()


# -- fleet integrity plane (obs/audit.py + durable/cut.py) -------------------

def digest(endpoint: str, timeout: Optional[float] = None):
    """Per-table content digests of any serving endpoint — primary,
    replica, or standby serving reads — at its exact watermark:
    ``{"role", "endpoint", "watermark", "layout_version", "tables":
    {tid: {"digest", "rows"}}}``. Order-independent over (id,
    row-bytes), so primaries, replicas and tiered/plain interchanges
    compare equal iff their applied state is equal. Slot-free."""
    from multiverso_tpu.runtime.remote import fetch_digest
    if timeout is None:
        timeout = float(get_flag("audit_timeout_seconds"))
    return fetch_digest(endpoint, timeout=timeout)


def audit(fleet, interval: Optional[float] = None,
          manifest: Optional[Dict[str, Any]] = None):
    """The continuous fleet auditor (obs/audit.py): compare
    primary↔replica state digests at a common watermark and check the
    acked-Add conservation ledger across probes; on mismatch fire
    ``AUDIT_DIVERGENCE`` through the flight-recorder path with both
    digests and the watermark vector attached. Returns a
    :class:`~multiverso_tpu.obs.audit.FleetAuditor` — already running in
    the background when ``interval`` (or the ``audit_interval_seconds``
    flag) is > 0; call ``.check()`` yourself for a one-shot report."""
    from multiverso_tpu.obs.audit import FleetAuditor
    auditor = FleetAuditor(fleet, interval=interval, manifest=manifest)
    if auditor.interval > 0:
        auditor.start()
    return auditor


def autopilot(group, interval: Optional[float] = None,
              auditor: Any = None, **kwargs: Any):
    """The fleet autopilot (multiverso_tpu/autopilot/): a periodic
    control loop over a live :class:`~multiverso_tpu.shard.group.
    ShardGroup` that reads the telemetry plane — per-shard heat,
    read-tier pressure, replica lag, tier hit rates, the SLO burn
    engine — and reshapes the fleet through the existing crash-safe
    machinery: hot-shard splits / cold-range merges via the
    MigrationCoordinator, live replica add/remove, tier budget
    rebalance. Safety first: pass the running ``mv.audit`` auditor as
    ``auditor`` and any ``AUDIT_DIVERGENCE`` freezes the loop until an
    operator ``.ack()``; every decision (and its rejected alternatives)
    lands in the flight recorder. Returns a
    :class:`~multiverso_tpu.autopilot.Autopilot` — already ticking in
    the background when ``interval`` (or the
    ``autopilot_interval_seconds`` flag) is > 0; call ``.tick_now()``
    yourself for deterministic drills, ``.status()`` for the operator
    view, ``.stop()`` to halt (docs/autopilot.md)."""
    # the multiverso_tpu.autopilot PACKAGE shares this name: importing it
    # rebinds the attribute to the module, which is callable with these
    # exact semantics (autopilot/__init__.py) — delegate so both the
    # pre-import function and the post-import module behave identically
    import multiverso_tpu.autopilot as _ap
    return _ap(group, interval=interval, auditor=auditor, **kwargs)


def cut_fleet(fleet, cut_id: Optional[str] = None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
    """Take a watermark-consistent cut of a serving fleet
    (durable/cut.py): fan the slot-free ``Control_Cut`` marker over
    every shard primary — each drains its dispatcher, snapshots at its
    ``WalWriter.seq`` fence, replies fence + digests — and commit the
    atomic fleet manifest under ``<base_dir>/cuts/``. ``fleet`` is a
    ShardGroup or its base_dir. Returns the committed manifest; raises
    (committing NOTHING) if any member failed mid-cut."""
    from multiverso_tpu.durable.cut import cut_fleet as _cut
    return _cut(fleet, cut_id=cut_id, timeout=timeout)


def restore_fleet(manifest=None, base_dir: Optional[str] = None,
                  replicas: int = 0, standby: bool = False,
                  timeout: float = 240.0):
    """Point-in-time recovery (durable/cut.py): bring up a fresh
    ShardGroup restored to a committed cut — every shard at the SAME
    manifest's fence, dedup windows seeded from the cut's acked-Add
    ledger. ``manifest`` is a cut manifest dict, a fleet base_dir (its
    LATEST cut), or a manifest path. Returns the started ShardGroup."""
    from multiverso_tpu.durable.cut import restore_fleet as _restore
    return _restore(manifest, base_dir=base_dir, replicas=replicas,
                    standby=standby, timeout=timeout)


def clone_fleet(source, base_dir: Optional[str] = None, replicas: int = 0,
                timeout: float = 240.0):
    """Blue/green bring-up (durable/cut.py): bootstrap a fresh
    ShardGroup from a LIVE fleet — each clone shard absorbs one quiesced
    ``Control_Replicate`` transfer from its source primary, then serves
    under its own WAL lineage. ``source`` is a ShardGroup, its base_dir,
    or a cut manifest (endpoints name the donors). Returns the started
    clone group."""
    from multiverso_tpu.durable.cut import clone_fleet as _clone
    return _clone(source, base_dir=base_dir, replicas=replicas,
                  timeout=timeout)


# -- raw net mode (MV_NetBind / MV_NetConnect / MV_NetFinalize) --------------
# External (off-mesh) hosts — the reference's CNTK/C# deployment shape
# (include/multiverso/multiverso.h:60-65, ZMQ Bind/Connect mode) — drive the
# transport directly without starting the PS runtime.

_raw_net = None


def net_bind(rank: int, endpoint: str) -> str:
    """Listen on ``host:port`` (port 0 → ephemeral); returns the bound
    endpoint."""
    global _raw_net
    from multiverso_tpu.runtime.net import TcpNet
    if _raw_net is None:
        _raw_net = TcpNet()
    return _raw_net.bind(rank, endpoint)


def net_connect(endpoints: Optional[Sequence[str]] = None) -> None:
    """Provide the full rank→endpoint map; connections dial lazily. With no
    argument, the map is read from the ``machine_file`` flag (one host:port
    per line — the reference ZMQ backend's ``ParseMachineFile`` contract,
    zmq_net.h:234-254)."""
    if _raw_net is None:
        log.fatal("net_connect: call net_bind first")
    if endpoints is None:
        from multiverso_tpu.runtime.net import parse_machine_file
        path = get_flag("machine_file")
        if not path:
            log.fatal("net_connect: no endpoints given and the machine_file "
                      "flag is empty")
        endpoints = parse_machine_file(path)
    _raw_net.connect(list(endpoints))


def net_finalize() -> None:
    global _raw_net
    if _raw_net is not None:
        _raw_net.finalize()
        _raw_net = None


def net() :
    """The raw-net transport (None until net_bind)."""
    return _raw_net


# -- tables -----------------------------------------------------------------

from multiverso_tpu.tables.array_table import ArrayServer, ArrayWorker  # noqa: E402
from multiverso_tpu.tables.kv_table import (  # noqa: E402
    DeviceKVServer, KVServer, KVWorker, TieredKVServer, make_tiered_kv)
from multiverso_tpu.tables.matrix_table import MatrixServer, MatrixWorker  # noqa: E402
from multiverso_tpu.tables.sparse_table import (  # noqa: E402
    SparseWorker, TieredSparseServer, make_tiered_sparse)
from multiverso_tpu.updaters import AddOption, GetOption  # noqa: E402,F401

ArrayTableHandler = ArrayWorker  # python-binding names
MatrixTableHandler = MatrixWorker

_TABLE_TYPES = {
    "array": ArrayWorker,
    "matrix": MatrixWorker,
    "kv": KVWorker,
    "sparse": SparseWorker,
    # beyond-RAM variants (multiverso_tpu/store/, docs/tiered_storage.md)
    "tiered_sparse": make_tiered_sparse,
    "tiered_kv": make_tiered_kv,
}


def create_table(kind: str, *args: Any, **kwargs: Any):
    """``MV_CreateTable`` parity: construct a worker/server table pair (the
    server side registers with the dispatcher automatically)."""
    try:
        cls = _TABLE_TYPES[kind]
    except KeyError:
        log.fatal("unknown table kind %r (have: %s)", kind, sorted(_TABLE_TYPES))
    table = cls(*args, **kwargs)
    # table creation happens once per process and is collective under a
    # multihost mesh — Zoo.register_table already rendezvoused processes
    return table


def register_table_type(kind: str, factory: Any) -> None:
    """Table-extension API: reference apps register custom tables
    (LogisticRegression's Sparse/FTRL tables); same seam here."""
    _TABLE_TYPES[kind] = factory
