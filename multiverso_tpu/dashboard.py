"""Named section timers (Monitor/Dashboard) — tracing & profiling subsystem.

Reference capability (not copied): statically-registered named section timers
via ``MONITOR_BEGIN/END`` macros aggregating count/total/average, with a
global ``Dashboard::Watch/Display`` (``include/multiverso/dashboard.h:16-75``,
``src/dashboard.cpp:14-49``).

TPU-era additions: monitors double as ``jax.profiler.TraceAnnotation`` scopes
when profiling is enabled, so named sections show up in TPU traces; the timer
is a context manager / decorator instead of macro pairs. The registry also
holds the telemetry subsystem's units (``multiverso_tpu/obs/``): monotonic
``Counter``\\ s, log-bucketed ``Histogram``\\ s (every ``monitor`` section
records its duration distribution, not just the average), and point-in-time
``Gauge``\\ s. ``snapshot()`` serializes the whole registry for the stats
RPC / metrics JSONL; ``render(format="prom")`` emits Prometheus text
exposition. Metric catalog: ``docs/observability.md``.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, Iterator, Optional

from contextlib import contextmanager

try:  # profiler annotations are optional — pure-host use works without jax
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None


class Monitor:
    """count / total-elapse / average for one named code section.

    The in-progress start time is THREAD-LOCAL: two threads timing the
    same named section concurrently each measure their own span (a single
    shared slot would let thread B's ``begin`` overwrite thread A's,
    corrupting both durations — the historical bug)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._count = 0
        self._elapse = 0.0  # seconds
        self._tls = threading.local()  # per-thread in-progress start time
        self._lock = threading.Lock()

    def begin(self) -> None:
        self._tls.begin = time.perf_counter()

    def end(self) -> None:
        begin = getattr(self._tls, "begin", None)
        if begin is None:
            return
        self._tls.begin = None
        self.observe(time.perf_counter() - begin)

    def observe(self, seconds: float) -> None:
        """Record one completed span (the begin/end pair fused — what the
        ``monitor`` context manager calls with its own local clock)."""
        with self._lock:
            self._count += 1
            self._elapse += seconds

    @property
    def count(self) -> int:
        return self._count

    @property
    def elapse_ms(self) -> float:
        return self._elapse * 1e3

    @property
    def average_ms(self) -> float:
        return self.elapse_ms / self._count if self._count else 0.0

    def reset(self) -> None:
        with self._lock:
            self._count = 0
            self._elapse = 0.0
            self._tls = threading.local()

    def __repr__(self) -> str:
        return (f"Monitor({self.name}: count={self.count}, "
                f"elapse={self.elapse_ms:.3f}ms, average={self.average_ms:.3f}ms)")


class Counter:
    """Monotonic event counter — the fault subsystem's observability unit
    (retries, reconnects, evictions, injected faults, dedup hits). Section
    timers (Monitor) measure durations; Counters record discrete events
    that have none."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def __repr__(self) -> str:
        return f"Counter({self.name}: {self.value})"


def _prom_name(name: str, suffix: str = "") -> str:
    base = re.sub(r"[^a-zA-Z0-9_]", "_", name).lower().strip("_")
    return f"mvtpu_{base}{suffix}"


# per-shard series names (ROUTER_SHARD3_SECONDS, FLEET_SHARD0_REPLICA_LAG)
# collapse into one labeled Prometheus family: the shard index moves from
# the metric name into a shard="3" label, so operators aggregate and
# alert across shards without a regex in every query
_SHARD_SERIES = re.compile(
    r"^(?P<pre>.+?)_SHARD(?P<idx>\d+)(?P<post>(?:_[A-Za-z0-9_]+)?)$")


def _split_shard(name: str):
    """``NAME_SHARD<k>_X`` -> (``NAME_X``, "k"); others -> (name, None)."""
    m = _SHARD_SERIES.match(name)
    if m is None:
        return name, None
    return m.group("pre") + m.group("post"), m.group("idx")


# per-tenant counter families (admission + chargeback planes) collapse
# the same way: TENANT_ctr_SHED becomes mvtpu_tenant_shed_total with a
# tenant="ctr" label. The suffix alternation is anchored so tenant names
# containing underscores (including "_default") split unambiguously.
_TENANT_SERIES = re.compile(
    r"^TENANT_(?P<tenant>.+)_(?P<suffix>ADMITTED|SHED|BYTES)$")


def split_tenant(name: str):
    """``TENANT_<t>_<SUFFIX>`` -> (``t``, ``SUFFIX``); others ->
    (None, None)."""
    m = _TENANT_SERIES.match(name)
    if m is None:
        return None, None
    return m.group("tenant"), m.group("suffix")


def _prom_escape(value: str) -> str:
    """Label-value escaping per the Prometheus text exposition format:
    backslash, double-quote and newline."""
    return (value.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


class Dashboard:
    """Global registry of monitors (reference: ``Dashboard::Watch/Display``)
    plus the telemetry units: counters, histograms, gauges."""

    _monitors: Dict[str, Monitor] = {}
    _counters: Dict[str, Counter] = {}
    _histograms: Dict[str, "object"] = {}  # name -> obs.metrics.Histogram
    _gauges: Dict[str, "object"] = {}      # name -> obs.metrics.Gauge
    _lock = threading.Lock()
    profile_annotations: bool = False

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = cls._monitors[name] = Monitor(name)
            return mon

    @classmethod
    def watch(cls, name: str) -> Optional[Monitor]:
        with cls._lock:
            return cls._monitors.get(name)

    @classmethod
    def counter(cls, name: str) -> Counter:
        with cls._lock:
            ctr = cls._counters.get(name)
            if ctr is None:
                ctr = cls._counters[name] = Counter(name)
            return ctr

    @classmethod
    def counter_value(cls, name: str) -> int:
        """Current count; 0 when the counter was never touched."""
        with cls._lock:
            ctr = cls._counters.get(name)
        return ctr.value if ctr is not None else 0

    @classmethod
    def histogram(cls, name: str, bounds=None):
        """Log-bucketed latency histogram (obs/metrics.py); created on
        first use like monitors/counters. ``bounds`` applies only at
        creation — count-valued histograms (rows per fused apply) pass
        unit-based geometric edges instead of the 1µs latency default,
        whose top edge (~134) they would overflow."""
        with cls._lock:
            hist = cls._histograms.get(name)
            if hist is None:
                # lazy import: dashboard is imported by everything, obs
                # only by what uses it — keeps the import graph acyclic
                from multiverso_tpu.obs.metrics import Histogram
                hist = cls._histograms[name] = Histogram(name, bounds=bounds)
            return hist

    @classmethod
    def gauge(cls, name: str):
        with cls._lock:
            g = cls._gauges.get(name)
            if g is None:
                from multiverso_tpu.obs.metrics import Gauge
                g = cls._gauges[name] = Gauge(name)
            return g

    @classmethod
    def gauge_value(cls, name: str) -> float:
        with cls._lock:
            g = cls._gauges.get(name)
        return g.value if g is not None else 0.0

    @classmethod
    def snapshot(cls) -> dict:
        """The whole registry as plain JSON-serializable data — the stats
        RPC payload, the metrics JSONL line, and the flight-recorder
        snapshot all share this one format."""
        with cls._lock:
            monitors = list(cls._monitors.values())
            counters = list(cls._counters.values())
            histograms = list(cls._histograms.values())
            gauges = list(cls._gauges.values())
        return {
            "monitors": {m.name: {"count": m.count,
                                  "elapse_ms": m.elapse_ms,
                                  "average_ms": m.average_ms}
                         for m in monitors},
            "counters": {c.name: c.value for c in counters},
            "gauges": {g.name: g.value for g in gauges},
            "histograms": {h.name: h.to_dict() for h in histograms},
        }

    @classmethod
    def render(cls, format: str = "text") -> str:
        """Operator-facing dump (returned, never printed; ``display()``
        keeps the reference's print-and-return contract).

        ``format="text"``: aligned monitor/counter/gauge/histogram tables
        an operator can read off a log or a debug endpoint.
        ``format="prom"``: Prometheus text exposition (counters/gauges/
        histograms with cumulative ``_bucket{le=...}`` rows) for scrape
        endpoints and pushgateways."""
        if format == "prom":
            return cls._render_prom()
        if format != "text":
            raise ValueError(f"render: unknown format {format!r} "
                             "(want 'text' or 'prom')")
        with cls._lock:
            monitors = list(cls._monitors.values())
            counters = list(cls._counters.values())
            histograms = list(cls._histograms.values())
            gauges = list(cls._gauges.values())
        lines = ["== dashboard =="]
        if monitors:
            lines.append(f"{'section':<36} {'count':>10} {'total_ms':>12} "
                         f"{'avg_ms':>10}")
            for m in monitors:
                lines.append(f"{m.name:<36} {m.count:>10} "
                             f"{m.elapse_ms:>12.3f} {m.average_ms:>10.3f}")
        if counters:
            lines.append(f"{'counter':<36} {'value':>10}")
            for c in counters:
                lines.append(f"{c.name:<36} {c.value:>10}")
        if gauges:
            lines.append(f"{'gauge':<36} {'value':>10}")
            for g in gauges:
                lines.append(f"{g.name:<36} {g.value:>10g}")
        if histograms:
            lines.append(f"{'histogram':<36} {'count':>8} {'p50_ms':>10} "
                         f"{'p95_ms':>10} {'p99_ms':>10} {'max_ms':>10}")
            for h in histograms:
                lines.append(f"{h.name:<36} {h.count:>8} "
                             f"{h.p50 * 1e3:>10.3f} {h.p95 * 1e3:>10.3f} "
                             f"{h.p99 * 1e3:>10.3f} {h.max * 1e3:>10.3f}")
        if not (monitors or counters or gauges or histograms):
            lines.append("(no monitors or counters recorded)")
        return "\n".join(lines)

    @classmethod
    def identity(cls) -> Dict[str, str]:
        """This process's fleet identity as Prometheus labels, from the
        ``metrics_shard`` / ``metrics_role`` flags (set by ``mv.serve``,
        shard-group children and replicas at startup). Empty when
        neither is set — single-process dashboards stay label-free."""
        from multiverso_tpu import config
        labels: Dict[str, str] = {}
        try:
            shard = int(config.get_flag("metrics_shard"))
            role = str(config.get_flag("metrics_role"))
        except Exception:  # noqa: BLE001 — render before flag definition
            return labels
        if shard >= 0:
            labels["shard"] = str(shard)
        if role:
            labels["role"] = role
        return labels

    @classmethod
    def set_identity(cls, shard: Optional[int] = None,
                     role: Optional[str] = None) -> None:
        """Stamp the process's fleet identity (flag-backed, so a
        dashboard reset does not lose it)."""
        from multiverso_tpu import config
        if shard is not None:
            config.set_flag("metrics_shard", int(shard))
        if role is not None:
            config.set_flag("metrics_role", str(role))

    @classmethod
    def _render_prom(cls) -> str:
        with cls._lock:
            monitors = list(cls._monitors.values())
            counters = list(cls._counters.values())
            histograms = list(cls._histograms.values())
            gauges = list(cls._gauges.values())
        ident = cls.identity()

        def lab(shard: Optional[str], le: Optional[str] = None,
                tenant: Optional[str] = None) -> str:
            labels = dict(ident)
            if shard is not None:
                # a per-shard series names its OWN shard — it wins over
                # the process identity (a launcher holding the fleet's
                # ROUTER_SHARD<k> series has no shard identity anyway)
                labels["shard"] = shard
            if tenant is not None:
                labels["tenant"] = tenant
            parts = [f'{k}="{_prom_escape(v)}"'
                     for k, v in sorted(labels.items())]
            if le is not None:
                parts.append(f'le="{le}"')
            return "{" + ",".join(parts) + "}" if parts else ""

        lines: list = []
        typed = set()

        def head(n: str, kind: str) -> None:
            # one # TYPE line per family — shard-labeled series of one
            # family share it
            if n not in typed:
                typed.add(n)
                lines.append(f"# TYPE {n} {kind}")

        for c in counters:
            tenant, suffix = split_tenant(c.name)
            if tenant is not None:
                n = _prom_name(f"TENANT_{suffix}")
                head(n, "counter")
                lines.append(
                    f"{n}_total{lab(None, tenant=tenant)} {c.value}")
                continue
            family, shard = _split_shard(c.name)
            n = _prom_name(family)
            head(n, "counter")
            lines.append(f"{n}_total{lab(shard)} {c.value}")
        for g in gauges:
            family, shard = _split_shard(g.name)
            n = _prom_name(family)
            head(n, "gauge")
            lines.append(f"{n}{lab(shard)} {g.value:g}")
        for m in monitors:
            family, shard = _split_shard(m.name)
            n = _prom_name(family)
            head(f"{n}_seconds", "summary")
            lines.append(f"{n}_seconds_sum{lab(shard)} "
                         f"{m.elapse_ms / 1e3:.9g}")
            lines.append(f"{n}_seconds_count{lab(shard)} {m.count}")
        for h in histograms:
            family, shard = _split_shard(h.name)
            n = _prom_name(family)
            data = h.to_dict()
            head(n, "histogram")
            cum = 0
            for bound, bucket in zip(data["bounds"], data["buckets"]):
                cum += bucket
                lines.append(
                    f'{n}_bucket{lab(shard, le=f"{bound:.9g}")} {cum}')
            lines.append(f'{n}_bucket{lab(shard, le="+Inf")} '
                         f'{data["count"]}')
            lines.append(f"{n}_sum{lab(shard)} {data['sum']:.9g}")
            lines.append(f"{n}_count{lab(shard)} {data['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    @classmethod
    def display(cls) -> str:
        with cls._lock:
            lines = ["--------------Dashboard--------------------"]
            lines.extend(repr(m) for m in cls._monitors.values())
            lines.extend(repr(c) for c in cls._counters.values())
            lines.extend(repr(g) for g in cls._gauges.values())
            lines.extend(repr(h) for h in cls._histograms.values())
        # the "why is it slow" panel rides along once the sampling
        # profiler has data (rendered OUTSIDE the registry lock)
        from multiverso_tpu.obs.profiler import PROFILER
        if PROFILER.samples:
            lines.append(PROFILER.render())
        text = "\n".join(lines)
        print(text, flush=True)
        return text

    @classmethod
    def reset(cls) -> None:
        """Zero every registered object IN PLACE. Clearing the dicts
        instead would orphan cached references: a module that held on to
        ``Dashboard.counter("X")`` would keep bumping an object no longer
        in the registry while readers see a fresh zero forever."""
        with cls._lock:
            objs = (list(cls._monitors.values())
                    + list(cls._counters.values())
                    + list(cls._histograms.values())
                    + list(cls._gauges.values()))
        for obj in objs:
            obj.reset()


@contextmanager
def monitor(name: str) -> Iterator[Monitor]:
    """``MONITOR_BEGIN(name) ... MONITOR_END(name)`` as a context manager.
    The duration feeds BOTH the monitor (count/total/average) and the
    same-named histogram (p50/p95/p99) — every timed section gets a
    distribution for free. Timing is a local on the caller's stack, so
    overlapping scopes on any thread mix cannot corrupt each other."""
    mon = Dashboard.get(name)
    t0 = time.perf_counter()
    ann = None
    if Dashboard.profile_annotations and _TraceAnnotation is not None:
        ann = _TraceAnnotation(name)
        ann.__enter__()
    try:
        yield mon
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        dt = time.perf_counter() - t0
        mon.observe(dt)
        Dashboard.histogram(name).observe(dt)


def count(name: str, n: int = 1) -> None:
    """Bump a named event counter (``Dashboard.counter(name).add(n)``)."""
    Dashboard.counter(name).add(n)


def observe(name: str, seconds: float) -> None:
    """Record one sample into a named histogram."""
    Dashboard.histogram(name).observe(seconds)


def gauge_set(name: str, value: float) -> None:
    """Set a named gauge (last writer wins)."""
    Dashboard.gauge(name).set(value)


def gauge_add(name: str, delta: float = 1.0) -> None:
    """Atomically add to a named gauge."""
    Dashboard.gauge(name).add(delta)


class Timer:
    """Chrono stopwatch in ms (reference: ``util/timer.h``)."""

    def __init__(self) -> None:
        self.start()

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def elapse_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3
