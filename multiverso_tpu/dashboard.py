"""Named section timers (Monitor/Dashboard) — tracing & profiling subsystem.

Reference capability (not copied): statically-registered named section timers
via ``MONITOR_BEGIN/END`` macros aggregating count/total/average, with a
global ``Dashboard::Watch/Display`` (``include/multiverso/dashboard.h:16-75``,
``src/dashboard.cpp:14-49``).

TPU-era additions: monitors double as ``jax.profiler.TraceAnnotation`` scopes
when profiling is enabled, so named sections show up in TPU traces; the timer
is a context manager / decorator instead of macro pairs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, Optional

from contextlib import contextmanager

try:  # profiler annotations are optional — pure-host use works without jax
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None


class Monitor:
    """count / total-elapse / average for one named code section."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._count = 0
        self._elapse = 0.0  # seconds
        self._begin: Optional[float] = None
        self._lock = threading.Lock()

    def begin(self) -> None:
        self._begin = time.perf_counter()

    def end(self) -> None:
        if self._begin is None:
            return
        dt = time.perf_counter() - self._begin
        self._begin = None
        with self._lock:
            self._count += 1
            self._elapse += dt

    @property
    def count(self) -> int:
        return self._count

    @property
    def elapse_ms(self) -> float:
        return self._elapse * 1e3

    @property
    def average_ms(self) -> float:
        return self.elapse_ms / self._count if self._count else 0.0

    def reset(self) -> None:
        with self._lock:
            self._count = 0
            self._elapse = 0.0
            self._begin = None

    def __repr__(self) -> str:
        return (f"Monitor({self.name}: count={self.count}, "
                f"elapse={self.elapse_ms:.3f}ms, average={self.average_ms:.3f}ms)")


class Counter:
    """Monotonic event counter — the fault subsystem's observability unit
    (retries, reconnects, evictions, injected faults, dedup hits). Section
    timers (Monitor) measure durations; Counters record discrete events
    that have none."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def __repr__(self) -> str:
        return f"Counter({self.name}: {self.value})"


class Dashboard:
    """Global registry of monitors (reference: ``Dashboard::Watch/Display``)."""

    _monitors: Dict[str, Monitor] = {}
    _counters: Dict[str, Counter] = {}
    _lock = threading.Lock()
    profile_annotations: bool = False

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = cls._monitors[name] = Monitor(name)
            return mon

    @classmethod
    def watch(cls, name: str) -> Optional[Monitor]:
        with cls._lock:
            return cls._monitors.get(name)

    @classmethod
    def counter(cls, name: str) -> Counter:
        with cls._lock:
            ctr = cls._counters.get(name)
            if ctr is None:
                ctr = cls._counters[name] = Counter(name)
            return ctr

    @classmethod
    def counter_value(cls, name: str) -> int:
        """Current count; 0 when the counter was never touched."""
        with cls._lock:
            ctr = cls._counters.get(name)
        return ctr.value if ctr is not None else 0

    @classmethod
    def render(cls) -> str:
        """Operator-facing text dump — aligned monitor/counter tables an
        operator can read off a log or a debug endpoint without touching
        the Python API (returned, never printed; ``display()`` keeps the
        reference's print-and-return contract)."""
        with cls._lock:
            monitors = list(cls._monitors.values())
            counters = list(cls._counters.values())
        lines = ["== dashboard =="]
        if monitors:
            lines.append(f"{'section':<36} {'count':>10} {'total_ms':>12} "
                         f"{'avg_ms':>10}")
            for m in monitors:
                lines.append(f"{m.name:<36} {m.count:>10} "
                             f"{m.elapse_ms:>12.3f} {m.average_ms:>10.3f}")
        if counters:
            lines.append(f"{'counter':<36} {'value':>10}")
            for c in counters:
                lines.append(f"{c.name:<36} {c.value:>10}")
        if not monitors and not counters:
            lines.append("(no monitors or counters recorded)")
        return "\n".join(lines)

    @classmethod
    def display(cls) -> str:
        with cls._lock:
            lines = ["--------------Dashboard--------------------"]
            lines.extend(repr(m) for m in cls._monitors.values())
            lines.extend(repr(c) for c in cls._counters.values())
        text = "\n".join(lines)
        print(text, flush=True)
        return text

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()
            cls._counters.clear()


@contextmanager
def monitor(name: str) -> Iterator[Monitor]:
    """``MONITOR_BEGIN(name) ... MONITOR_END(name)`` as a context manager."""
    mon = Dashboard.get(name)
    mon.begin()
    ann = None
    if Dashboard.profile_annotations and _TraceAnnotation is not None:
        ann = _TraceAnnotation(name)
        ann.__enter__()
    try:
        yield mon
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        mon.end()


def count(name: str, n: int = 1) -> None:
    """Bump a named event counter (``Dashboard.counter(name).add(n)``)."""
    Dashboard.counter(name).add(n)


class Timer:
    """Chrono stopwatch in ms (reference: ``util/timer.h``)."""

    def __init__(self) -> None:
        self.start()

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def elapse_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3
