"""Typed flag registry — TPU-native re-design of Multiverso's configure system.

Reference capability (not copied): a gflags-like static registration system
(``include/multiverso/util/configure.h:20-114``, ``src/util/configure.cpp:9-54``)
with ``MV_DEFINE_<type>(name, default, text)`` macros, ``-name=value`` CLI
parsing that compacts argv, and programmatic ``MV_SetFlag``.

This module provides the same capability surface for the TPU rebuild:

* ``define_int / define_bool / define_string / define_double`` — typed flag
  registration with defaults and help text.
* ``parse_cmd_flags(argv)`` — parses ``-name=value`` (and ``--name=value``)
  tokens, removes them from argv, returns the compacted list.
* ``set_flag(name, value)`` / ``get_flag(name)`` — programmatic access used by
  bindings (the reference's Python binding passes ``-sync=true`` as fake argv;
  here both paths hit the same registry).

Flags are process-global, matching the reference's static registry semantics.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


class FlagError(ValueError):
    """Raised on unknown flag access or unparsable flag values."""


def _parse_bool(text: str) -> bool:
    t = text.strip().lower()
    if t in ("true", "1", "yes", "on"):
        return True
    if t in ("false", "0", "no", "off"):
        return False
    raise FlagError(f"cannot parse boolean flag value: {text!r}")


@dataclass
class _Flag:
    name: str
    value: Any
    default: Any
    parser: Callable[[str], Any]
    help_text: str


class FlagRegistry:
    """Thread-safe typed flag store. One global instance (`FLAGS`) mirrors the
    reference's static registry; separate instances exist for tests."""

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.RLock()
        # per-flag change watchers (on_change seam): controllers and cached
        # hot-path readers subscribe instead of polling get_flag
        self._watchers: Dict[str, List[Callable[[str, Any], None]]] = {}

    # -- registration ------------------------------------------------------
    def define(self, name: str, default: Any, parser: Callable[[str], Any],
               help_text: str = "") -> None:
        with self._lock:
            if name in self._flags:
                # Re-definition keeps the first registration, like static init.
                return
            self._flags[name] = _Flag(name, default, default, parser, help_text)

    def define_int(self, name: str, default: int, help_text: str = "") -> None:
        self.define(name, int(default), int, help_text)

    def define_bool(self, name: str, default: bool, help_text: str = "") -> None:
        self.define(name, bool(default), _parse_bool, help_text)

    def define_string(self, name: str, default: str, help_text: str = "") -> None:
        self.define(name, str(default), str, help_text)

    def define_double(self, name: str, default: float, help_text: str = "") -> None:
        self.define(name, float(default), float, help_text)

    # -- access ------------------------------------------------------------
    def get(self, name: str) -> Any:
        with self._lock:
            try:
                return self._flags[name].value
            except KeyError:
                raise FlagError(f"unknown flag: {name!r}") from None

    def set(self, name: str, value: Any) -> None:
        """Programmatic set (``MV_SetFlag`` parity). Accepts either the typed
        value or a string to be parsed with the flag's parser."""
        with self._lock:
            try:
                flag = self._flags[name]
            except KeyError:
                raise FlagError(f"unknown flag: {name!r}") from None
            if isinstance(value, str) and not isinstance(flag.default, str):
                new = flag.parser(value)
            else:
                new = type(flag.default)(value)
            changed = new != flag.value
            flag.value = new
        if changed:
            self._notify(name, new)

    def reset(self, name: Optional[str] = None) -> None:
        changed: List[tuple] = []
        with self._lock:
            if name is None:
                for f in self._flags.values():
                    if f.value != f.default:
                        changed.append((f.name, f.default))
                    f.value = f.default
            else:
                f = self._flags[name]
                if f.value != f.default:
                    changed.append((f.name, f.default))
                f.value = f.default
        for n, v in changed:
            self._notify(n, v)

    # -- change watchers ----------------------------------------------------
    def on_change(self, name: str,
                  callback: Callable[[str, Any], None]) -> Callable[[], None]:
        """Subscribe ``callback(name, new_value)`` to value changes of flag
        ``name`` (fired by set/reset/parse_cmd_flags, only when the value
        actually changes). Returns an unsubscribe function. Callbacks run
        OUTSIDE the registry lock (they may read other flags) and their
        exceptions are swallowed — a broken watcher must not poison set_flag."""
        with self._lock:
            if name not in self._flags:
                raise FlagError(f"unknown flag: {name!r}")
            self._watchers.setdefault(name, []).append(callback)

        def unsubscribe() -> None:
            with self._lock:
                cbs = self._watchers.get(name, [])
                try:
                    cbs.remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    def _notify(self, name: str, value: Any) -> None:
        with self._lock:
            cbs = list(self._watchers.get(name, ()))
        for cb in cbs:
            try:
                cb(name, value)
            except Exception:
                pass

    def known(self, name: str) -> bool:
        with self._lock:
            return name in self._flags

    def items(self) -> Dict[str, Any]:
        with self._lock:
            return {k: f.value for k, f in self._flags.items()}

    # -- CLI ---------------------------------------------------------------
    def parse_cmd_flags(self, argv: Optional[List[str]]) -> List[str]:
        """Parse ``-key=value`` / ``--key=value`` tokens; unknown flags and
        non-flag tokens are kept, parsed flags are removed (argv compaction,
        matching the reference parser's contract)."""
        if not argv:
            return []
        remaining: List[str] = []
        for token in argv:
            if token.startswith("-") and "=" in token:
                key, _, raw = token.lstrip("-").partition("=")
                with self._lock:
                    flag = self._flags.get(key)
                    if flag is not None:
                        new = flag.parser(raw)
                        changed = new != flag.value
                        flag.value = new
                if flag is not None:
                    if changed:
                        self._notify(key, new)
                    continue
            remaining.append(token)
        return remaining


# Process-global registry (reference: static registry in configure.cpp).
FLAGS = FlagRegistry()

define_int = FLAGS.define_int
define_bool = FLAGS.define_bool
define_string = FLAGS.define_string
define_double = FLAGS.define_double
get_flag = FLAGS.get
set_flag = FLAGS.set
on_flag_change = FLAGS.on_change
parse_cmd_flags = FLAGS.parse_cmd_flags


# Core runtime flags (superset of the reference's flag list, §2.1 "Config"):
define_string("ps_role", "default", "node role: worker|server|default(all)|none")
define_bool("ma", False, "model-averaging mode: skip PS tables, aggregate() only")
define_bool("sync", False, "synchronous (BSP) parameter server")
define_int("ssp_staleness", -1,
           "stale-synchronous-parallel bound: a worker's Get waits until "
           "every unfinished worker is within this many add-rounds of it "
           "(0 = BSP-like read gate; -1 disables). Ignored when sync=True")
define_double("backup_worker_ratio", 0.0,
              "fraction of workers treated as backups: the BSP round gates "
              "ignore the slowest floor(ratio*num_workers) workers' clocks")
define_double("sync_stall_seconds", 30.0,
              "BSP watchdog period: log which workers' clocks are holding a "
              "round when deferred requests make no progress; 0 disables")
define_string("updater_type", "default", "server-side optimizer: default|sgd|adagrad|momentum_sgd|dcasgd")
define_int("omp_threads", 4, "host-side worker threads for CPU fallbacks")
define_bool("is_pipelined", False, "double-buffered pipelined get")
define_int("allocator_alignment", 16, "host buffer alignment (native allocator)")
define_string("allocator_type", "smart", "host allocator: smart|default")
define_string("machine_file", "", "multi-host machine list (external transport)")
define_int("port", 55555, "external transport port")
define_int("wire_quant_bits", 0,
           "quantize remote ADD deltas to this many bits per value "
           "(1|2|4|8) with client-side error feedback — the OneBitsFilter "
           "slot, generalized; 0 disables")
define_int("wire_coalesce_frames", 64,
           "max frames one vectored send syscall carries on the host wire "
           "(runtime/net.py drain loop): frames queued while a send is in "
           "flight flush together via socket.sendmsg. 0 = legacy per-frame "
           "sendall (also disables the zero-copy queue)")
define_int("wire_coalesce_bytes", 1 << 20,
           "max payload bytes one coalesced send syscall carries; a frame "
           "larger than this still ships alone (never split). 0 = legacy "
           "per-frame sendall")
define_int("apply_batch_msgs", 64,
           "max queued Adds the dispatcher fuses into ONE table apply per "
           "drain (runtime/server.py): the async server drains its queue "
           "each wakeup, groups Adds by table, merges duplicate rows and "
           "applies each group as a single jitted/pallas scatter. Bounds "
           "completion latency and host-side merge cost. 0 = legacy "
           "per-message dispatch (BSP/SSP/deterministic servers always "
           "apply per message — their round gates serialize adds)")
define_int("apply_batch_rows", 16384,
           "max rows one fused matrix apply covers: the merge consumes a "
           "prefix of the drained group up to this many rows and the rest "
           "fuse in the next call — bounds the power-of-two id-bucket "
           "(and its zero-padded upload) a runaway batch would inflate. "
           "0 = unbounded")
define_bool("wire_shm", False,
            "negotiate a shared-memory ring transport at connect for "
            "colocated client/server processes (runtime/shm.py): same v3 "
            "framing + CRC + req-id contract as TCP, so dedup/retransmit/"
            "tracing/chaos seams are unchanged; falls back to TCP "
            "transparently when the peer is remote, has the flag off, or "
            "cannot map the segment")
define_int("wire_shm_bytes", 4 << 20,
           "shared-memory ring capacity per direction (bytes, rounded to "
           "a multiple of 8); frames larger than the ring stream through "
           "it in chunks")
define_int("wire_shm_spin", 20,
           "busy-spin iterations of the shm ring wait ladder before it "
           "starts yielding (then sleeping): the latency/CPU-burn knob the "
           "autotuner backs off when shm_ring_spin wait dominates; read "
           "live on the wait path. 0 = yield immediately")
define_string("wire_shm_dir", "",
              "directory for shm ring segment files; empty = /dev/shm "
              "when present, else the system temp dir")
define_string("multihost_endpoint", "",
              "host:port the leader (JAX process 0) binds for the multihost "
              "lockstep control plane; same value on every process")
define_double("multihost_timeout", 120.0,
              "multihost control-plane connect/barrier timeout (seconds)")
define_int("multihost_window", 64,
           "max follower-origin table ops in flight to the leader before "
           "the forwarding worker blocks (windowed pipelined control "
           "plane; acks complete out of a reorder buffer). 0 = unbounded")
define_string("multihost_token", "",
              "shared secret authenticating multihost control-plane "
              "handshakes (HMAC-SHA256 over the hello frames); empty gives "
              "integrity-only framing — see docs/multihost.md trust model")
define_string("mesh_shape", "", "device mesh shape, e.g. '2x4'; empty = auto 1-D")
define_bool("profile_annotations", False,
            "wrap dashboard monitor sections in jax.profiler.TraceAnnotation "
            "so SERVER_PROCESS_* device time shows up in profiler traces")
define_string("trace_dir", "",
              "start a jax.profiler trace into this directory at init and "
              "stop it at shutdown (implies profile_annotations)")
define_string("mesh_axes", "server", "comma-separated mesh axis names")
define_bool("deterministic", False,
            "async PS applies adds in (round, worker_id) order so the final "
            "table state is bitwise reproducible (DeterministicServer)")

# Fault subsystem (multiverso_tpu/fault/): injection, retry/replay, liveness.
define_string("fault_spec", "",
              "fault-injection schedule applied to host transports "
              "(fault/inject.py): ';'-separated rules "
              "'action:key=val,key=val' with actions drop|delay|dup|reorder|"
              "partition, predicates src/dst/type/table and limiters "
              "first/after/every/prob (delay takes seconds=). Empty disables")
define_int("fault_seed", 0,
           "seed for probabilistic fault rules (prob=) so chaos runs replay")
define_double("request_retry_seconds", 5.0,
              "remote client retransmit timeout: a correlated request with "
              "no reply after this long is re-sent (exponentially backed "
              "off); the server's req-id dedup window keeps the replay "
              "idempotent. 0 disables retransmission")
define_double("reconnect_deadline_seconds", 20.0,
              "total budget for a remote client's reconnect-and-resume "
              "after a connection loss before pending requests fail; "
              "0 restores the fail-fast posture (no reconnect)")
define_double("retry_base_seconds", 0.05,
              "reconnect backoff base: attempt k sleeps "
              "~base*2^(k-1), jittered, capped by retry_cap_seconds")
define_double("retry_cap_seconds", 2.0,
              "upper bound on a single reconnect backoff sleep")
define_double("heartbeat_seconds", 2.0,
              "remote client lease-renewal period (Control_Heartbeat); "
              "0 disables heartbeats (disable lease eviction too)")
define_double("lease_seconds", 10.0,
              "remote worker lease: the sync watchdog evicts a worker whose "
              "last sign of life (heartbeat or any request) is older than "
              "this, releasing BSP/SSP rounds it was holding; 0 disables")
define_int("dedup_window", 4096,
           "server-side request-id dedup window (entries) bounding the "
           "idempotent-replay cache for retried remote requests")

# Durability subsystem (multiverso_tpu/durable/): WAL + restart recovery +
# warm-standby failover (docs/fault_tolerance.md §7).
define_string("wal_dir", "",
              "durability root: when set, serve() write-ahead-logs every "
              "remote Add (CRC-checksummed records under <wal_dir>/wal/) "
              "before it is ACKed; restart recovery = mv.durable_recover() "
              "(snapshot + WAL replay + dedup-window rebuild), compaction "
              "= CheckpointDriver(..., wal=mv.wal_writer()). Empty disables")

# Tiered beyond-RAM storage (multiverso_tpu/store/): hot/cold row tiers
# for the sparse/KV table kinds (docs/tiered_storage.md).
define_int("tier_resident_bytes", 64 << 20,
           "hot-tier byte budget per tiered table: row payload bytes kept "
           "RAM-resident; the LRU tail past it is demoted to quantized "
           "cold segments on disk")
define_int("tier_cold_bits", 8,
           "quantization width for cold-tier rows (1/2/4/8, float32 tables "
           "only — Seide et al. 2014 packing, lossy by ≤ step/2 per "
           "element); 0 stores raw bytes (lossless, any dtype)")
define_string("tier_dir", "",
              "cold-tier spill root (one root per process, like wal_dir): "
              "each tiered table spills under <tier_dir>/tier<ordinal>, "
              "reused+wiped across restarts. Empty = fresh tempdir per "
              "table (spill is per-incarnation; durability is snapshot+WAL)")
define_int("tier_admit_touches", 2,
           "frequency-sketch touches a cold key needs before a Get promotes "
           "it back to the hot tier (second-chance admission: a one-shot "
           "scan cannot thrash the Zipf-hot working set); Adds always "
           "promote")

# Telemetry subsystem (multiverso_tpu/obs/): latency histograms, gauges,
# per-request tracing, flight recorder, metrics JSONL, stats RPC
# (docs/observability.md).
define_string("metrics_path", "",
              "append periodic JSONL dashboard snapshots (monitors, "
              "counters, gauges, histograms as bucket arrays) to this file "
              "— the format bench.py's load_metrics ingests. Empty disables "
              "the MetricsLogger thread")
define_double("metrics_interval_seconds", 10.0,
              "seconds between metrics_path snapshot lines")
define_string("flight_recorder_path", "",
              "append flight-recorder dumps (event + dashboard snapshot + "
              "the last flight_recorder_traces per-request hop traces, one "
              "JSON object per line) to this file on worker eviction, "
              "standby failover, frame CRC reject, or a client failing all "
              "pending requests. Empty disables dumping")
define_int("flight_recorder_traces", 256,
           "how many recent request traces each flight-recorder dump "
           "includes (the in-memory trace ring holds at least this many)")
# Fleet observability plane (obs/collector.py, obs/timeseries.py,
# obs/slo.py; docs/observability.md): cross-process trace stitching,
# windowed time-series, SLO burn-rate alerts.
define_bool("trace_requests", True,
            "stamp the v4 header's trace flag on every correlated "
            "request, so forwarded/derived frames (router parts, read "
            "confirms, multihost forwards) keep recording under the "
            "originating req_id; hop recording itself is always on for "
            "nonzero req_ids — this flag only controls propagation")
define_bool("trace_read_confirm", True,
            "a traced replica-served Get additionally fires a slot-free "
            "Control_Watermark frame at the primary stamped with the "
            "SAME req_id — the trace then spans client, replica AND the "
            "primary watermark path, and the client's cache horizon "
            "advances off the authoritative append watermark")
define_int("trace_export_max", 256,
           "how many recent traces a Control_Traces reply ships (each "
           "process's trace ring holds 512)")
define_double("timeseries_interval_seconds", 1.0,
              "seconds between time-series recorder samples of every "
              "registered counter/gauge/histogram; 0 disables the "
              "sampler thread (manual sample_now() still works)")
define_int("timeseries_samples", 600,
           "ring-buffer length per metric in the time-series recorder "
           "(retention = this many * timeseries_interval_seconds)")
define_string("slo_spec", "",
              "declarative SLOs, ';'-separated: "
              "name:histogram=H,p=0.99,target=SEC[,windows=SHORT/LONG] | "
              "name:counter=C,target=PER_SEC[,windows=...] | "
              "name:gauge=G,target=VALUE. A firing burn alert increments "
              "SLO_BURN_ALERTS and triggers a tagged flight-recorder "
              "dump. Empty disables the engine")
define_double("slo_check_interval_seconds", 5.0,
              "seconds between SLO engine evaluations; 0 disables the "
              "engine thread (manual evaluate_now() still works)")
# Sampling profiler + critical-path attribution (obs/profiler.py,
# obs/critpath.py; docs/observability.md §13): the "why is it slow"
# layer — on/off-CPU sampling with named wait sites, PROFILE_* gauges,
# capture-on-alert, Control_Profile pulls, mv.attribution(fleet).
define_double("profile_hz", 50.0,
              "sampling rate of the continuous profiler's frame walker "
              "(samples per second over sys._current_frames()); values "
              "<= 0 fall back to 50")
define_bool("profile_continuous", False,
            "start the process-wide sampling profiler inside mv.init and "
            "feed PROFILE_* counters/gauges into the dashboard (and so "
            "the time-series recorder) on every sampling pass")
define_bool("profile_on_alert", True,
            "attach a sampling-profiler report to every slo_burn flight "
            "dump: the continuous profiler's report when it is running, "
            "otherwise a short synchronous burst capture (~50ms)")
define_int("profile_max_frames", 24,
           "stack-depth cap per collapsed (flamegraph) stack; deeper "
           "stacks keep their leaf-most frames")
define_int("flight_recorder_max_bytes", 64 << 20,
           "size cap for the flight_recorder_path file: once it is at "
           "least this large, further dumps are suppressed (counted in "
           "FLIGHT_DUMPS_SUPPRESSED) instead of filling the disk; "
           "0 = unlimited")
define_double("flight_recorder_min_interval_seconds", 0.0,
              "per-REASON rate limit for flight-recorder dumps: a dump "
              "whose reason fired within this many seconds is suppressed "
              "(counted in FLIGHT_DUMPS_SUPPRESSED); 0 disables the "
              "rate limit — a flapping alert should set this to O(10s)")
define_double("audit_interval_seconds", 0.0,
              "period of the continuous fleet auditor (mv.audit): every "
              "interval it pulls Control_Digest from each primary and "
              "replica, compares them at a common watermark and fires "
              "AUDIT_DIVERGENCE through the flight-recorder path on "
              "mismatch; 0 = one-shot checks only (no background thread)")
define_double("audit_timeout_seconds", 30.0,
              "per-endpoint timeout for Control_Digest / Control_Cut "
              "probes: a dead or wedged member lands on the audit "
              "report's unreachable list (or fails the cut) instead of "
              "hanging the coordinator")
define_double("stats_timeout_seconds", 5.0,
              "per-endpoint timeout for the mv.stats_all fan-out: a dead "
              "or wedged endpoint lands on the merged snapshot's "
              "unreachable list instead of stalling the whole probe")
define_int("metrics_shard", -1,
           "this process's shard index for Prometheus labels "
           "(mvtpu_*{shard=...}); -1 omits the label")
define_string("metrics_role", "",
              "this process's serving role for Prometheus labels "
              "(primary|replica|standby); empty omits the label. serve() "
              "and replica/standby startup set it when unset")
# Sharded serving tier (multiverso_tpu/shard/): table partitioning,
# client-side router, shard groups with per-shard failover
# (docs/sharding.md).
define_int("shards", 0,
           "shard count for sharded serving (mv.serve_sharded spawns one "
           "serving process per shard); 0 = unsharded single server")
define_string("shard_partitioner", "auto",
              "partitioner for key tables in a shard group: auto|range|"
              "hash (array/matrix rows are always range-partitioned); "
              "unknown values fail fast with the accepted set")
define_string("shard_endpoints", "",
              "comma-separated host:port members of an existing shard "
              "group — mv.shard_connect() bootstraps the layout manifest "
              "from the first reachable member; entries are validated "
              "fail-fast")
# Elastic membership / live key-range migration (shard/reshard.py:
# split/merge/move under traffic; docs/sharding.md §live migration).
define_bool("auto_reshard", False,
            "let the hot-range detector EXECUTE the splits it proposes "
            "(MigrationCoordinator.maybe_autosplit); off, detection only "
            "proposes (RESHARD_PROPOSALS counter + log line)")
define_double("reshard_hot_ratio", 3.0,
              "hot-range detector threshold: a shard proposes for a split "
              "when its request rate exceeds this multiple of the median "
              "shard's rate over the observation window")
define_double("reshard_min_qps", 50.0,
              "hot-range detector floor: shards below this request rate "
              "never propose a split regardless of skew (splitting an "
              "idle group is churn, not balance)")
define_double("reshard_cold_qps", 5.0,
              "cold-range detector ceiling: two ADJACENT shards both "
              "below this request rate propose a merge (the inverse of "
              "the split path — an over-split group wastes processes)")
# Fleet autopilot (multiverso_tpu/autopilot/): the control loop that
# reads the telemetry plane and reshapes the fleet (docs/autopilot.md).
define_double("autopilot_interval_seconds", 5.0,
              "autopilot control-loop tick period; <= 0 disables the "
              "background thread (tick_now() still works for drills)")
define_int("autopilot_hysteresis_ticks", 2,
           "consecutive ticks a condition must hold before the autopilot "
           "acts on it — one noisy sample must not resize the fleet")
define_double("autopilot_cooldown_seconds", 60.0,
              "per-action cooldown after the autopilot executes (or "
              "fails) an action of that kind; re-deciding inside the "
              "window is recorded as a rejected alternative")
define_double("autopilot_window_seconds", 30.0,
              "observation window the autopilot's sensors read rates "
              "and per-shard heat over (also the hot-range detector's "
              "window when the autopilot constructs it)")
define_int("autopilot_max_replicas", 4,
           "ceiling on serving read replicas per shard the autopilot "
           "may scale up to")
define_int("autopilot_min_replicas", 0,
           "floor on serving read replicas per shard the autopilot may "
           "scale down to")
define_double("autopilot_hedge_rate", 5.0,
              "read-tier pressure threshold (hedges + refusals + "
              "primary fallbacks per second): sustained pressure above "
              "this proposes adding a read replica")
define_double("autopilot_scaledown_qps", 1.0,
              "fleet-wide request-rate floor: sustained traffic below "
              "this proposes removing a read replica (down to "
              "autopilot_min_replicas)")
define_double("autopilot_tier_target_hit_rate", 0.90,
              "tiered-store hot-tier hit-rate target: sustained hit "
              "rate below this grows the resident budget by "
              "autopilot_tier_step_bytes (up to autopilot_tier_max_bytes)")
define_int("autopilot_tier_step_bytes", 16 << 20,
           "bytes the autopilot grows/shrinks the tier_resident_bytes "
           "budget by per rebalance action")
define_int("autopilot_tier_max_bytes", 512 << 20,
           "ceiling the autopilot may grow tier_resident_bytes to")
define_bool("autopilot_blue_green", False,
            "rehearse risky topology changes (split/merge) on an "
            "mv.clone_fleet canary before executing them live; off, the "
            "autopilot executes directly through the crash-safe "
            "MigrationCoordinator path")
# Self-tuning runtime (multiverso_tpu/tune/): attribution-driven feedback
# controller that steps the perf knobs above and reverts regressions
# (docs/autotune.md).
define_bool("autotune", False,
            "start the KnobController inside mv.init: a windowed "
            "sense→propose→step→verify loop that reads the profiler's "
            "wait sites + the time-series windows, steps ONE bounded perf "
            "knob at a time (apply_batch_msgs, wire_coalesce_*, "
            "wire_quant_bits, wire_shm_spin, read_hedge_ms, "
            "client_cache_bytes, tier_admit_touches) and reverts any step "
            "whose windowed objective regresses. Off = bit-identical "
            "runtime (no thread, no TUNE_* metrics)")
define_double("autotune_interval_seconds", 2.0,
              "KnobController tick period; <= 0 disables the background "
              "thread (tick_now() still works for drills and bench legs)")
define_double("autotune_window_seconds", 10.0,
              "observation window the tuner's sensors read wait-site "
              "deltas, rates and latency quantiles over (also the "
              "objective's measurement window)")
define_int("autotune_hysteresis_ticks", 2,
           "consecutive ticks a dominant cost must hold before the tuner "
           "steps the mapped knob — one noisy sample must not move a flag")
define_double("autotune_cooldown_seconds", 10.0,
              "per-knob cooldown after a committed or reverted step; "
              "re-proposing inside the window is recorded as a rejected "
              "alternative in the decision trail")
define_int("autotune_verify_ticks", 2,
           "ticks the tuner waits after stepping a knob before comparing "
           "the windowed objective against the pre-step baseline (the "
           "verify phase; no other knob moves while one is in flight)")
define_double("autotune_regress_pct", 5.0,
              "objective regression tolerance: a stepped knob whose "
              "verify-phase objective lands more than this percent below "
              "the pre-step baseline is reverted (TUNE_REVERTS) and its "
              "direction cooled down; within tolerance it commits")
# Read-replica serving tier (durable/standby.py serve loop + runtime/read.py
# client-side cache and routing; docs/serving.md).
define_int("replicas", 0,
           "serving read replicas per shard in a shard group (each tails "
           "the primary's WAL and answers slot-free watermark-stamped "
           "Gets); 0 = none. Implies durability (replication tails the "
           "WAL)")
define_string("read_preference", "primary",
              "where a remote client's Gets go: primary (every Get takes "
              "a primary worker slot — the pre-replica behavior), replica "
              "(round-robin over read replicas whose replay watermark "
              "satisfies the staleness budget, falling back to the "
              "primary when none qualifies), hedged (replica, plus a "
              "second endpoint fired after a p95-derived delay; first "
              "reply wins, the loser is cancelled)")
define_int("read_staleness_records", 1024,
           "staleness budget for replica-served Gets, in WAL records: a "
           "replica may answer only while its replay watermark is within "
           "this many records of the primary's append watermark "
           "(generalized SSP bound — clocks become reads); -1 = unbounded "
           "(any live replica answers)")
define_int("client_cache_bytes", 0,
           "client-side bounded-staleness read cache capacity (bytes, "
           "LRU by table/key): a cached Get is served without touching "
           "the wire while its watermark stays within "
           "read_staleness_records of the newest watermark the client "
           "has observed AND its lease (read_lease_seconds) is live. "
           "0 disables the cache")
define_double("read_lease_seconds", 0.25,
              "client cache entry lease: the blind window during which a "
              "cached read may be re-served without any wire contact "
              "(watermark invalidation still applies the instant a newer "
              "watermark is observed)")
define_double("read_timeout_seconds", 1.0,
              "deadline for one replica read attempt before the client "
              "falls back (next replica, then primary); also the cap on "
              "the hedged second-fire delay")
define_double("read_hedge_ms", 0.0,
              "hedged-read second-fire delay in milliseconds; 0 derives "
              "it from the p95 of recent replica read latencies")
define_string("wal_sync", "batch",
              "WAL durability barrier per append: none (buffered — the "
              "tail can be lost even to a process crash), batch (flush to "
              "the OS — survives kill -9, not power loss; the default), "
              "always (fsync — survives power loss, slowest)")
define_double("request_deadline_seconds", 0.0,
              "per-request deadline budget clients stamp on correlated "
              "requests (Get/Add/Read); it rides the wire header as "
              "REMAINING microseconds, re-anchored on each receiver's "
              "monotonic clock, and the server dispatcher drops expired "
              "work at drain time with deadline_exceeded instead of "
              "applying it. 0 = no deadline (legacy peers' 0-stamped "
              "frames are likewise never refused)")
define_bool("priority_lanes", True,
            "stably sort each dispatcher drain into lanes: serving reads "
            "(admin/slot-free Gets) > control > training traffic. Stable "
            "within a lane, so per-worker FIFO is preserved; forced off "
            "on the deterministic server (arrival-order WAL contract)")
define_int("admission_queue_limit", 0,
           "dispatcher backlog (messages) above which the admission gate "
           "sheds wire training writes with a truthful 'shed: ...' reply "
           "(serving reads shed only at 4x this limit — brownout before "
           "blackout). 0 disables backlog shedding")
define_string("tenant_quota_spec", "",
              "per-tenant write-admission quotas keyed by table "
              "namespace: ';'-separated "
              "name:tables=<id>|<id>,qps=<rate>[,burst=<cap>] entries — "
              "a tenant that exhausts its token bucket has its own Adds "
              "shed (TENANT_<name>_SHED) without touching other tenants "
              "or the serving lane. Empty = no quotas")
define_double("deadline_tighten_ratio", 0.0,
              "floor fraction of request_deadline_seconds the client "
              "shrinks minted deadlines toward while the SLO burn engine "
              "fires (geometric per-mint steps both down and back up, "
              "every transition flight-recorded) so backlog age tracks "
              "the error budget. 0 disables: minting is bit-identical to "
              "the plain request_deadline_seconds path")
define_double("retry_budget_tokens", 0.0,
              "per-connection retry budget: token bucket capacity spent "
              "by retransmits, read hedges, and layout re-fetches, "
              "refilled retry_budget_ratio per success — under overload "
              "retry pressure decays to the refill rate instead of "
              "storming. A denial defers the retry (never fails the "
              "request) and counts RETRY_BUDGET_DENIALS. 0 = unlimited")
define_double("retry_budget_ratio", 0.1,
              "retry-budget refill per successful reply (tokens); the "
              "steady-state retry rate is bounded at this fraction of "
              "the success rate")
define_int("breaker_failures", 0,
           "consecutive request failures (retransmit timeouts, "
           "connection-loss recoveries) that trip a client connection's "
           "circuit breaker open: writes fail fast with a truthful "
           "'circuit open' error and reads stop falling back to the "
           "primary (replicas keep serving) until a half-open probe "
           "succeeds. 0 disables the breaker")
define_double("breaker_reset_seconds", 5.0,
              "how long a tripped breaker stays open before admitting "
              "one half-open probe; the probe's outcome closes or "
              "re-opens it")
