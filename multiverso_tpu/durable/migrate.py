"""Range-filtered WAL tailing for live key-range migration.

The recipient half of the shard tier's split/merge/move protocol
(``shard/reshard.py``, docs/sharding.md §migration): a joining shard
subscribes to each donor's WAL stream restricted to the id ranges it is
taking over, absorbs a quiesced raw-value transfer of exactly those
ranges, then tails ``Control_Wal_Record`` frames — translating each Add
from donor-local to recipient-local ids and dropping the parts outside
its ranges — until the coordinator's cutover watermark is reached.

Zero-acknowledged-Add-loss inherits the warm-standby argument
(``durable/standby.py``): the donor writes every replication frame to
the subscriber's socket BEFORE the client's ACK, records carry their
append sequence for gap detection, and records that race the transfer
reply buffer until the transfer's watermark decides which suffix
replays. A detected gap resubscribes for a fresh transfer — safe here
because ``absorb_range`` overwrites raw values (idempotent), unlike an
incremental add replay.

What deliberately does NOT migrate: updater state (momentum/adagrad
accumulators reset on the recipient, like a v1 checkpoint restore) and
the donor's dedup window (a ``Reply_WrongShard`` refusal strictly
implies not-applied, so the router re-issues under a FRESH req_id — no
replayed-id collision is possible on the recipient).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import count, gauge_set
from multiverso_tpu.fault.detector import LivenessDetector
from multiverso_tpu.fault.inject import make_net
from multiverso_tpu.runtime import wire
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id

_DONOR = 0  # the lease id the donor is tracked under


def translate_add(kind: str, request: Any, donor_lo: int, donor_hi: int,
                  rcpt_start: int, rcpt_size: int = 0,
                  num_col: int = 0) -> Optional[Any]:
    """Rewrite one donor-coordinate Add request into recipient
    coordinates, restricted to the migrating donor-local range
    [donor_lo, donor_hi). Returns None when nothing overlaps. Pure — unit
    tested standalone (tests/test_reshard.py).

    ``rcpt_start`` is the recipient-local id the range lands at;
    ``rcpt_size`` (array) / ``num_col`` (matrix) shape the rewritten
    payload. Whole-span donor adds become explicit-id (matrix) or
    zero-padded whole-span (array) recipient adds — both exact under the
    commutative-Add contract."""
    span = donor_hi - donor_lo
    if kind == "matrix":
        row_ids, values, option = request
        values = np.asarray(values)
        if row_ids is None:
            rows = values.reshape(-1, num_col)
            if donor_lo >= rows.shape[0]:
                return None
            hi = min(donor_hi, rows.shape[0])
            ids = np.arange(hi - donor_lo, dtype=np.int32) + rcpt_start
            return ids, rows[donor_lo:hi], option
        row_ids = np.asarray(row_ids, dtype=np.int32).reshape(-1)
        mask = (row_ids >= donor_lo) & (row_ids < donor_hi)
        if not mask.any():
            return None
        ids = (row_ids[mask] - donor_lo + rcpt_start).astype(np.int32)
        return ids, values.reshape(len(row_ids), -1)[mask], option
    if kind == "array":
        delta = np.asarray(request[0]).reshape(-1)
        option = request[1]
        if donor_lo >= delta.size:
            return None
        hi = min(donor_hi, delta.size)
        out = np.zeros(rcpt_size, dtype=delta.dtype)
        out[rcpt_start:rcpt_start + (hi - donor_lo)] = delta[donor_lo:hi]
        if not out.any():
            return None
        return out, option
    log.fatal("translate_add: unsupported table kind %r", kind)
    return None


class RangeTailer:
    """Tails ONE donor's WAL for the migrating ranges of a joining shard.

    ``specs`` is a list of per-table dicts::

        {"table_id": <donor table id>, "server_table": <recipient table>,
         "kind": "matrix"|"array", "donor_lo": .., "donor_hi": ..,
         "rcpt_start": .., "rcpt_size": .., "num_col": ..}

    with donor_lo/donor_hi DONOR-local ids and rcpt_start the
    recipient-local id the range lands at. Construct inside the joining
    process (after its tables exist), then ``start()``; the coordinator
    cuts the donor over and hands the watermark to ``wait_watermark``.
    """

    def __init__(self, donor_endpoint: str, specs: List[Dict[str, Any]],
                 zoo=None, lease_seconds: Optional[float] = None) -> None:
        from multiverso_tpu.runtime.zoo import Zoo
        self._zoo = zoo if zoo is not None else Zoo.instance()
        self.donor_endpoint = donor_endpoint
        self._specs = {int(s["table_id"]): s for s in specs}
        self._detector = LivenessDetector(
            float(lease_seconds if lease_seconds is not None
                  else config.get_flag("lease_seconds")))
        self.applied_watermark = -1
        self.received_watermark = -1
        self.donor_watermark = -1
        self.records_applied = 0
        self.synced = threading.Event()
        self.failed = threading.Event()
        self.error: str = ""
        self._stop = threading.Event()
        self._awaiting_transfer = False
        self._pretransfer: List[Message] = []
        self._net = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RangeTailer":
        self._net = make_net()
        self._net.rank = -1
        self._net.connect([self.donor_endpoint])
        self._send_subscribe()  # raises if the donor is unreachable now
        self._detector.register(_DONOR)
        for name, target in (("mv-migrate-pump", self._pump),
                             ("mv-migrate-watch", self._watch)):
            thread = threading.Thread(target=target, daemon=True, name=name)
            thread.start()
            self._threads.append(thread)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._net is not None:
            self._net.finalize()
        for thread in self._threads:
            thread.join(timeout=10)
        self._threads.clear()

    def lag_records(self) -> int:
        if self.applied_watermark < 0 or self.donor_watermark < 0:
            return 0
        return max(0, self.donor_watermark - self.applied_watermark)

    def wait_watermark(self, watermark: int, timeout: float) -> None:
        """Block until every record through ``watermark`` has applied —
        the catch-up barrier between the donor's cutover reply and the
        recipient starting to serve."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.applied_watermark >= watermark:
                return
            if self.failed.is_set():
                raise ConnectionError(
                    f"migration tail of {self.donor_endpoint} failed: "
                    f"{self.error or 'donor lost'}")
            time.sleep(0.01)
        raise TimeoutError(
            f"migration catch-up to watermark {watermark} timed out "
            f"(applied {self.applied_watermark})")

    # -- replication stream --------------------------------------------------
    def _send_subscribe(self) -> None:
        self._awaiting_transfer = True
        ranges = {tid: [int(s["donor_lo"]), int(s["donor_hi"])]
                  for tid, s in self._specs.items()}
        self._net.send(Message(src=-1, dst=0, type=MsgType.Control_Migrate,
                               msg_id=next_msg_id(),
                               data=wire.encode({"tables": ranges})))

    def _fail(self, why: str) -> None:
        self.error = why
        self.failed.set()

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self._net.recv()
            except ConnectionError:
                if self._stop.is_set():
                    return
                self._awaiting_transfer = False
                self._pretransfer.clear()
                self._resubscribe()
                continue
            if msg is None:
                return
            self._detector.beat(_DONOR)
            try:
                if msg.type == MsgType.Control_Wal_Record:
                    self._on_record(msg)
                elif msg.type == MsgType.Control_Reply_Migrate:
                    self._awaiting_transfer = False
                    self._load_transfer(wire.decode(msg.data))
                elif msg.type == MsgType.Control_Heartbeat:
                    if msg.watermark > self.donor_watermark:
                        self.donor_watermark = msg.watermark
                        self._lag_gauge()
                elif msg.type == MsgType.Reply_Error:
                    self._fail("donor refused migration subscribe: "
                               f"{wire.decode(msg.data) if msg.data else '?'}")
                    return
            except Exception as exc:  # noqa: BLE001 — a dead pump fakes a
                # donor death; resubscribe (absorb is idempotent)
                log.error("migrate: pump failed on %s (%r) — resubscribing",
                          msg.type, exc)
                try:
                    self._send_subscribe()
                except OSError:
                    pass  # conn dying; the ConnectionError path redials

    def _on_record(self, msg: Message) -> None:
        seq = int(msg.watermark)
        if seq > self.donor_watermark:
            self.donor_watermark = seq
            self._lag_gauge()
        if self._awaiting_transfer or self.received_watermark < 0:
            self._pretransfer.append(msg)
            return
        self._accept_record(msg)

    def _accept_record(self, msg: Message) -> None:
        seq = int(msg.watermark)
        if seq >= 0 and self.received_watermark >= 0:
            if seq <= self.received_watermark:
                return  # duplicate: already applied
            if seq != self.received_watermark + 1:
                # stream gap: the local range copy has a hole. Resync via
                # a fresh transfer — absorb_range overwrites raw values,
                # so re-absorbing plus re-tailing is exact
                count("MIGRATION_GAP_RESYNCS")
                log.error("migrate: replication gap (have %d, got %d) — "
                          "resubscribing", self.received_watermark, seq)
                self._pretransfer.clear()
                self._awaiting_transfer = True
                try:
                    self._send_subscribe()
                except OSError:
                    pass  # conn is dying; _resubscribe redials
                return
        self.received_watermark = max(self.received_watermark, seq)
        self._apply(msg)

    def _resubscribe(self) -> None:
        while (not self._stop.is_set()
               and not self._detector.is_evicted(_DONOR)):
            time.sleep(0.2)
            if self._stop.is_set() or self._detector.is_evicted(_DONOR):
                break
            try:
                self._send_subscribe()
                log.info("migrate: donor stream re-established")
                return
            except OSError:
                continue
        if not self._stop.is_set():
            self._fail("donor connection lost past the lease")

    def _run(self, fn):
        server = self._zoo.server
        if server is None or not hasattr(server, "run_serialized"):
            return fn()
        return server.run_serialized(fn)

    def _load_transfer(self, payload: Any) -> None:
        tables = payload.get("tables", {})
        watermark = int(payload.get("watermark", -1))

        def run():
            for table_id, values in tables.items():
                spec = self._specs.get(int(table_id))
                if spec is None:
                    continue
                spec["server_table"].absorb_range(int(spec["rcpt_start"]),
                                                  values)
            self.applied_watermark = watermark
            self.received_watermark = watermark

        self._run(run)
        if watermark > self.donor_watermark:
            self.donor_watermark = watermark
        backlog = sorted(self._pretransfer, key=lambda m: int(m.watermark))
        self._pretransfer = []
        self._lag_gauge()
        self.synced.set()
        log.info("migrate: range transfer complete (%d table(s), "
                 "watermark %d, %d raced record(s))", len(tables),
                 watermark, len(backlog))
        for msg in backlog:
            if int(msg.watermark) > watermark:
                self._accept_record(msg)

    def _apply(self, msg: Message) -> None:
        seq = int(msg.watermark)
        spec = self._specs.get(int(msg.table_id))
        translated = None
        if spec is not None:
            request = wire.decode(msg.data)
            translated = translate_add(
                spec["kind"], request, int(spec["donor_lo"]),
                int(spec["donor_hi"]), int(spec["rcpt_start"]),
                rcpt_size=int(spec.get("rcpt_size", 0)),
                num_col=int(spec.get("num_col", 0)))
        if translated is None:
            # outside the migrating ranges (or an untracked table): the
            # watermark still advances — catch-up measures stream
            # position, not payload relevance
            if seq >= 0:
                self.applied_watermark = max(self.applied_watermark, seq)
            self._lag_gauge()
            return
        table = spec["server_table"]

        def run():
            table.process_add(translated)
            if seq >= 0:
                self.applied_watermark = seq

        self._run(run)
        self.records_applied += 1
        self._lag_gauge()

    def _lag_gauge(self) -> None:
        gauge_set("MIGRATION_LAG_RECORDS", self.lag_records())

    def _watch(self) -> None:
        period = max(0.05, (self._detector.lease_seconds or 1.0) / 4.0)
        while not self._stop.wait(period):
            if _DONOR in self._detector.reap():
                self._fail("donor lease expired mid-migration")
                return
