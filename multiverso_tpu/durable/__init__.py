"""Durability subsystem: write-ahead logging, exactly-once restart
recovery, and warm-standby failover.

The missing half of the fault story (Li et al., OSDI'14): PR 1 made the
*wire* survive lost packets and dead workers, but the serving process was
still a single point of data loss — a crash discarded every acknowledged
Add since the last periodic snapshot, and the req-id dedup window died
with the process. This package closes that:

* :mod:`~multiverso_tpu.durable.wal` — per-table write-ahead log over the
  Stream layer (CRC-checksummed, length-prefixed records appended on the
  dispatcher thread before an Add is ACKed), snapshot-coupled segment
  rotation/compaction, and ``recover()`` = snapshot + WAL replay +
  dedup-window reconstruction, so exactly-once holds ACROSS restarts.
* :mod:`~multiverso_tpu.durable.standby` — a warm-standby server that
  tails the primary's WAL over a replication stream, detects primary
  death by lease expiry, and binds the service endpoint so client
  reconnect logic resumes against it transparently.
* :mod:`~multiverso_tpu.durable.migrate` — range-filtered WAL tailing
  for live key-range migration (shard/reshard.py): a joining shard
  absorbs a quiesced raw-value transfer of exactly the migrating
  ranges, then tails the donor's record stream — translating donor ids
  to its own — up to the cutover watermark.

See docs/fault_tolerance.md §7 for the operator story.
"""

import os as _os

from multiverso_tpu.durable.wal import (  # noqa: F401
    RecoveryResult, WalRecord, WalWriter, read_manifest, recover)
from multiverso_tpu.durable.standby import WarmStandby  # noqa: F401
from multiverso_tpu.durable.migrate import (  # noqa: F401
    RangeTailer, translate_add)


def shard_wal_dir(root: str, shard: int) -> str:
    """Per-shard durability root under a shard group's base directory:
    ``<root>/shard<k>``. One WAL + snapshot lineage per shard — a shard's
    crash/recovery/compaction never touches its peers' logs, and a
    restarted member finds its own manifest by shard id alone
    (docs/sharding.md)."""
    return _os.path.join(str(root), f"shard{int(shard)}")
