"""Watermark-consistent fleet cuts + point-in-time recovery/cloning.

Restoring a sharded fleet from each shard's latest independent snapshot
can resurrect a state no client ever observed: shard A's snapshot may
predate an acked Add whose sibling write to shard B is included. The fix
is a marker-based consistent cut (Chandy-Lamport shaped, simplified by
the system's model — clients talk to shards, shards never talk to each
other, so there are no in-flight cross-shard messages to capture):

* The coordinator (:func:`cut_fleet`) fans a slot-free ``Control_Cut``
  over every shard primary.
* Each primary — on its pump thread, the only thread that enqueues wire
  requests — runs ONE dispatcher-serialized block
  (:func:`capture_cut`): read the ``WalWriter.seq`` fence (the drain
  guarantees every acked Add is <= it), rotate the log so segments
  before/after the cut are physically disjoint, store every table and
  its content digest into ``<wal_dir>/cut_<id>/`` — deliberately
  OUTSIDE the ``gen_<g>`` compaction lineage, so later
  ``commit_snapshot`` retirements never collect a committed cut — and
  write the shard's ``CUT.json`` (fence, dedup Add-window, digests).
* The coordinator commits the atomic **fleet manifest**
  (``<base_dir>/cuts/cut_<id>.json`` + ``LATEST.json``, tmp+rename)
  only after EVERY member answered. A shard killed mid-cut (the
  ``MV_CUT_KILL`` drill) fails the whole cut; the previous manifest
  stays the recovery point.

Point-in-time recovery (:func:`restore_fleet`) brings up a fresh
:class:`~multiverso_tpu.shard.group.ShardGroup` in which every shard
loads its cut snapshot — the state at its fence, i.e. the WAL replay
truncated exactly there — and seeds its dedup window from ``CUT.json``,
so clients retrying pre-cut Adds are answered, not double-applied.
:func:`clone_fleet` bootstraps a blue/green twin of a LIVE fleet instead:
each clone shard pulls a quiesced full-state transfer over the existing
``Control_Replicate`` shape and serves it under a fresh WAL lineage.
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from multiverso_tpu import config, io as mv_io, log
from multiverso_tpu.dashboard import count, observe
from multiverso_tpu.runtime.message import Message, MsgType

CUT_META = "CUT.json"


# -- shard side ---------------------------------------------------------------

def capture_cut(remote, cut_id: str) -> Dict[str, Any]:
    """Snapshot this shard at its WAL fence (``Control_Cut`` handler
    body; ``remote`` is the serving RemoteServer). Runs everything that
    defines the cut — fence read, log rotation, table stores, digests,
    dedup capture, CUT.json commit — in ONE dispatcher-serialized block:
    no Add can land between the fence and the stores, so the snapshot IS
    the state at the fence."""
    from multiverso_tpu import checkpoint
    from multiverso_tpu.obs.audit import table_digest
    server = remote._zoo.server
    wal = server.wal
    t0 = time.perf_counter()
    fs = mv_io.fs_for(wal.directory)
    cut_dir = mv_io.join(wal.directory, f"cut_{cut_id}")

    def run():
        fence = int(wal.seq)
        segment = wal.rotate()  # pre-cut records live strictly below it
        fs.makedirs(cut_dir)
        digests: Dict[int, Dict[str, Any]] = {}
        table_ids: List[int] = []
        for tid, table in sorted(server._tables.items()):
            checkpoint.store_table(
                table, mv_io.join(cut_dir, f"table_{tid}.mvckpt"))
            digests[int(tid)] = table_digest(table)
            table_ids.append(int(tid))
        with remote._dedup_lock:
            dedup = [[m.req_id, m.dst, m.msg_id]
                     for m in remote._dedup.values()
                     if isinstance(m, Message)
                     and m.type == MsgType.Reply_Add]
        meta = {"cut_id": str(cut_id), "fence": fence, "segment": segment,
                "tables": table_ids, "digests": digests, "dedup": dedup}
        tmp = mv_io.join(cut_dir, CUT_META + ".tmp")
        with mv_io.get_stream(tmp, "w") as stream:
            stream.write(json.dumps(meta).encode("utf-8"))
        fs.replace(tmp, mv_io.join(cut_dir, CUT_META))
        return meta

    meta = server.run_serialized(run, timeout=None)
    count("CUT_SNAPSHOTS")
    observe("CUT_SNAPSHOT_SECONDS", time.perf_counter() - t0)
    log.info("cut: shard snapshot %s at fence %d -> %s", cut_id,
             meta["fence"], cut_dir)
    return {**meta, "cut_dir": cut_dir,
            "dedup_count": len(meta["dedup"]), "dedup": None}


# -- coordinator --------------------------------------------------------------

def _fleet_view(fleet: Any) -> Dict[str, Any]:
    """Normalize a fleet handle — ShardGroup, its ``base_dir``, or a cut
    manifest — into what the coordinator needs. Group handles resolve
    through the on-disk ``group.json`` + ``layout.json``, so a detached
    coordinator process (the chaos drills) can drive a cut knowing only
    the base directory."""
    if isinstance(fleet, dict) and "shards" in fleet:  # a cut manifest
        return {"base_dir": fleet.get("base_dir", ""),
                "endpoints": [s["endpoint"] for s in fleet["shards"]],
                "layout_version": int(fleet.get("layout_version", 1)),
                "num_shards": int(fleet["num_shards"]),
                "tables": fleet["tables"], "flags": fleet.get("flags", {}),
                "host": fleet.get("host", "127.0.0.1"),
                "wal_root": fleet.get("wal_root", "")}
    base_dir = fleet if isinstance(fleet, str) else getattr(
        fleet, "base_dir", None)
    if not base_dir:
        log.fatal("cut: cannot resolve a fleet from %r — pass a "
                  "ShardGroup, its base_dir, or a cut manifest", fleet)
    with open(os.path.join(base_dir, "group.json"), encoding="utf-8") as f:
        spec = json.load(f)
    with open(os.path.join(base_dir, "layout.json"), encoding="utf-8") as f:
        layout = json.load(f)
    return {"base_dir": base_dir,
            "endpoints": list(layout["endpoints"]),
            "layout_version": int(layout.get("layout_version", 1)),
            "num_shards": int(spec["num_shards"]),
            "tables": spec["tables"], "flags": spec.get("flags", {}),
            "host": spec.get("host", "127.0.0.1"),
            "wal_root": spec.get("wal_root", "")}


def cut_fleet(fleet: Any, cut_id: Optional[str] = None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
    """Take a consistent cut of a serving fleet and commit its manifest
    (``mv.cut_fleet``). Fans ``Control_Cut`` over every primary
    concurrently; commits atomically only when ALL answered — a partial
    cut is no cut (``CUT_FLEET_FAILURES``), and the previously committed
    manifest stays the fleet's recovery point.

    The ``MV_CUT_KILL`` chaos drill reads the env at cut time in THIS
    process: ``shard`` rides the cut payload and each primary SIGKILLs
    itself after its local snapshot but before replying; ``coordinator``
    SIGKILLs this process after the fan-out but before the manifest
    commit. Both leave the fleet restorable only to the previous cut —
    exactly the invariant tests/test_cut.py pins."""
    from multiverso_tpu.runtime.remote import fetch_cut
    view = _fleet_view(fleet)
    if timeout is None:
        timeout = float(config.get_flag("audit_timeout_seconds"))
    if cut_id is None:
        cut_id = f"{int(time.time() * 1000):x}-{os.getpid():x}"
    kill = os.environ.get("MV_CUT_KILL", "")
    results: Dict[int, Any] = {}
    errors: Dict[int, str] = {}
    lock = threading.Lock()

    def probe(k: int, ep: str) -> None:
        try:
            reply = fetch_cut(ep, cut_id, timeout=timeout,
                              kill=(kill if kill == "shard" else ""))
            with lock:
                results[k] = {"shard": k, "endpoint": ep, **reply}
        except (OSError, RuntimeError) as exc:
            with lock:
                errors[k] = f"{ep}: {exc}"

    threads = [threading.Thread(target=probe, args=(k, ep), daemon=True,
                                name="mv-cut-probe")
               for k, ep in enumerate(view["endpoints"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 5.0)
    if errors or len(results) != len(view["endpoints"]):
        count("CUT_FLEET_FAILURES")
        missing = [str(k) for k in range(len(view["endpoints"]))
                   if k not in results and k not in errors]
        raise RuntimeError(
            "cut_fleet: cut %s failed — the previous manifest remains the "
            "recovery point (errors: %s%s)" % (
                cut_id, "; ".join(errors.values()) or "none",
                f"; no reply from shard(s) {','.join(missing)}"
                if missing else ""))
    shards = [results[k] for k in sorted(results)]
    manifest = {"cut_id": cut_id, "committed_at": time.time(),
                "layout_version": view["layout_version"],
                "num_shards": view["num_shards"],
                "tables": view["tables"], "flags": view["flags"],
                "host": view["host"], "wal_root": view["wal_root"],
                "base_dir": view["base_dir"], "shards": shards,
                "watermarks": {s["endpoint"]: int(s["fence"])
                               for s in shards}}
    if kill == "coordinator":
        log.error("cut: MV_CUT_KILL=coordinator — dying before the "
                  "manifest commit (drill)")
        os.kill(os.getpid(), signal.SIGKILL)
    cuts_dir = os.path.join(view["base_dir"], "cuts")
    os.makedirs(cuts_dir, exist_ok=True)
    blob = json.dumps(manifest)
    for name in (f"cut_{cut_id}.json", "LATEST.json"):
        tmp = os.path.join(cuts_dir, name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(cuts_dir, name))  # atomic commit
    count("CUT_FLEET_COMMITS")
    log.info("cut: fleet manifest %s committed (%d shard(s), fences %s)",
             cut_id, len(shards), [s["fence"] for s in shards])
    return manifest


def load_cut_manifest(fleet: Any) -> Optional[Dict[str, Any]]:
    """The last COMMITTED cut manifest of a fleet (ShardGroup, base_dir,
    or a direct path to a manifest file); None when no cut ever
    committed."""
    if isinstance(fleet, str) and fleet.endswith(".json"):
        path = fleet
    else:
        base_dir = fleet if isinstance(fleet, str) else getattr(
            fleet, "base_dir", "")
        path = os.path.join(base_dir, "cuts", "LATEST.json")
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- point-in-time recovery / cloning ----------------------------------------

def restore_fleet(manifest: Any, base_dir: Optional[str] = None,
                  replicas: int = 0, standby: bool = False,
                  timeout: float = 240.0):
    """Bring up a fresh ShardGroup restored to a committed cut
    (``mv.restore_fleet``): every shard loads its ``cut_<id>/`` snapshot
    — the state at its fence, i.e. its WAL truncated exactly there — and
    seeds its dedup window from the cut's Add ledger, so clients
    retrying pre-cut Adds get their cached ACKs instead of
    double-applying. The new group runs a fresh WAL lineage under its
    own ``base_dir`` (the source fleet's log stays untouched — a botched
    restore can always be retried)."""
    from multiverso_tpu.shard.group import ShardGroup
    if isinstance(manifest, (str, type(None))) or hasattr(manifest,
                                                          "base_dir"):
        manifest = load_cut_manifest(manifest)
    if not manifest:
        log.fatal("restore_fleet: no committed cut manifest to restore "
                  "from")
    group = ShardGroup(manifest["tables"],
                       shards=int(manifest["num_shards"]),
                       base_dir=base_dir, durable=True, replicas=replicas,
                       standby=standby, flags=manifest.get("flags"),
                       host=manifest.get("host", "127.0.0.1"),
                       preplanned=True)
    for s in manifest["shards"]:
        group._primary_extra[int(s["shard"])] = ["--restore-cut",
                                                 s["cut_dir"]]
    group.start(timeout=timeout)
    log.info("restore: fleet restored to cut %s at %s",
             manifest["cut_id"], group.endpoints)
    return group


def clone_fleet(source: Any, base_dir: Optional[str] = None,
                replicas: int = 0, timeout: float = 240.0):
    """Bootstrap a blue/green twin of a LIVE fleet (``mv.clone_fleet``):
    each clone shard pulls a quiesced full-state transfer from its
    source primary over the existing ``Control_Replicate`` shape —
    tables, dedup Add-window and watermark in one dispatcher-serialized
    reply — then serves it under a fresh WAL lineage. ``source`` is a
    ShardGroup, its base_dir, or a cut manifest (whose per-shard
    endpoints name the donors)."""
    from multiverso_tpu.shard.group import ShardGroup
    view = _fleet_view(source)
    group = ShardGroup(view["tables"], shards=view["num_shards"],
                       base_dir=base_dir, durable=True, replicas=replicas,
                       flags=view["flags"], host=view["host"],
                       preplanned=True)
    for k, ep in enumerate(view["endpoints"]):
        group._primary_extra[k] = ["--clone-primary", ep]
    group.start(timeout=timeout)
    log.info("clone: fleet cloned from %s at %s", view["endpoints"],
             group.endpoints)
    return group
