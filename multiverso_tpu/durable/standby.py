"""Warm-standby failover: a second process that tails the primary's WAL.

Dean et al. (NIPS 2012) motivate the warm replica: async training at real
traffic cannot afford a cold restart — the replacement must already hold
the tables when the primary dies. :class:`WarmStandby` delivers that on
the existing wire machinery:

1. **Subscribe** — dial the primary and send ``Control_Replicate``; the
   reply is a quiesced full-state transfer (every table's checkpoint
   bytes + the Add half of the req-id dedup window).
2. **Tail** — the primary forwards every durable WAL append as a
   ``Control_Wal_Record`` frame; the standby applies it to its own tables
   on its dispatcher thread and accumulates the ``(req_id, worker,
   msg_id)`` seeds. Because the primary writes the replication frame
   before the client's ACK frame, an acknowledged Add is always on the
   standby's socket before the primary can die.
3. **Detect** — the primary's liveness rides a lease
   (:class:`~multiverso_tpu.fault.detector.LivenessDetector`): every
   record or heartbeat renews it; on connection loss the standby
   re-subscribes (full state transfer again — cheap insurance against a
   blip) while the lease keeps ticking.
4. **Take over** — when the lease expires, the standby binds the service
   endpoint (``mv.serve``) with its accumulated dedup seeds. Existing
   client retry/reconnect logic resumes against it transparently: resume
   claims are granted (fresh lease table), in-flight Adds retransmit, and
   the seeded dedup window keeps every replayed Add exactly-once.

The service endpoint must be one the clients can re-dial — same host:port
(this module's tests), a VIP, or DNS that fails over with the role.

**Serving read replica** (docs/serving.md): the standby already holds a
live, record-lag-fresh copy of every table — ``serve_reads()`` promotes
it into a read replica. A small listener answers slot-free
``Request_Read`` frames (no worker slot, no lease, no dedup entry),
serialized with the replay applies, each reply stamped with the replay
watermark (the WAL record sequence the replica has applied through). The
staleness contract is Ho et al.'s SSP bound generalized from clocks to
reads: a request carrying a staleness budget of B records is answered
only while ``primary append watermark − replay watermark ≤ B`` —
otherwise the replica refuses and the client falls back to the primary.
Every replicated record carries its append sequence, so a stream gap
(a chaos-dropped frame) is DETECTED and forces a resubscribe instead of
silently under-reporting the lag. ``takeover=False`` builds a pure read
replica (several can tail one primary; none races to bind its endpoint
when it dies — budget-bound reads refuse instead).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu import io as mv_io
from multiverso_tpu.dashboard import Dashboard, count, gauge_set, observe
from multiverso_tpu.fault.detector import LivenessDetector
from multiverso_tpu.obs.trace import flight_dump, hop
from multiverso_tpu.fault.inject import make_net
from multiverso_tpu.runtime import wire
from multiverso_tpu.runtime.contracts import slot_free
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id
from multiverso_tpu.utils.backoff import Backoff

_PRIMARY = 0  # the lease id the primary is tracked under


class WarmStandby:
    """Replicates a serving primary and takes over its endpoint on death.

    Construct AFTER ``mv.init`` + ``mv.create_table`` (same flags and
    table order as the primary, so table ids and worker-slot arithmetic
    line up), then ``start()``. ``wait_failover()`` blocks until takeover;
    ``stop()`` abandons the standby role cleanly.
    """

    def __init__(self, primary_endpoint: str, service_endpoint: str,
                 tables: Optional[List[Any]] = None,
                 lease_seconds: Optional[float] = None,
                 takeover: bool = True) -> None:
        from multiverso_tpu.runtime.zoo import Zoo
        self._zoo = Zoo.instance()
        if not self._zoo.started or self._zoo.server is None:
            log.fatal("WarmStandby: init() the PS runtime first")
        self._primary_endpoint = primary_endpoint
        self._service_endpoint = service_endpoint
        self.takeover = bool(takeover)
        source = tables if tables is not None else self._zoo._worker_tables
        self._tables: Dict[int, Any] = {}
        for table in source:
            server_table = getattr(table, "_server_table", table)
            self._tables[int(getattr(server_table, "table_id", 0))] = \
                server_table
        self._detector = LivenessDetector(
            float(lease_seconds if lease_seconds is not None
                  else config.get_flag("lease_seconds")))
        self._seeds: List[Tuple[int, int, int]] = []
        self.records_applied = 0
        self.endpoint: Optional[str] = None
        self.took_over = threading.Event()
        self.synced = threading.Event()
        self._stop = threading.Event()
        self._net = None
        self._threads: List[threading.Thread] = []
        # -- watermark state (read-replica tier) --
        # applied_watermark: last WAL record sequence APPLIED to the local
        # tables (the replay watermark stamped on read replies);
        # received_watermark: last sequence RECEIVED off the stream (may
        # run ahead of applied while the tail is held); primary_watermark:
        # the primary's append sequence as last advertised (records,
        # heartbeats, the transfer) — the lag read admission compares
        # against. All -1 until the first state transfer lands.
        self.applied_watermark = -1
        self.received_watermark = -1
        self.primary_watermark = -1
        self.last_contact = time.monotonic()
        # True once the primary's lease expired with takeover=False: the
        # lag is unbounded from here, budget-bound reads refuse
        self.primary_dead = False
        # test/ops seam ("artificially held-back tail"): while set,
        # records are received (watermarks advance, lag grows) but not
        # applied — release_tail() applies the backlog
        self.hold_tail = threading.Event()
        self._held: List[Message] = []
        self._awaiting_transfer = False
        # records that arrived while a state transfer was pending: the
        # primary forwards records from the dispatcher thread while the
        # transfer reply rides the pump thread, so records can reach us
        # BEFORE the snapshot that may or may not contain them. They are
        # buffered and the suffix past the transfer's watermark replays
        # after it loads — applying them early would be wiped by the
        # snapshot (acknowledged-Add loss on failover).
        self._pretransfer: List[Message] = []
        self._read_server: Optional[ReplicaReadServer] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "WarmStandby":
        self._net = make_net()
        self._net.rank = -1
        self._net.connect([self._primary_endpoint])
        self._send_subscribe()  # raises if the primary is unreachable now
        self._detector.register(_PRIMARY)
        for name, target in (("mv-standby-pump", self._pump),
                             ("mv-standby-watch", self._watch)):
            thread = threading.Thread(target=target, daemon=True, name=name)
            thread.start()
            self._threads.append(thread)
        return self

    def stop(self) -> None:
        """Abandon the standby role (no takeover)."""
        self._stop.set()
        if self._read_server is not None:
            self._read_server.stop()
            self._read_server = None
        if self._net is not None:
            self._net.finalize()
        for thread in self._threads:
            thread.join(timeout=10)
        self._threads.clear()

    def serve_reads(self, endpoint: str = "127.0.0.1:0") -> str:
        """Promote this standby into a serving read replica: bind a
        listener answering slot-free ``Request_Read`` / ``Control_Stats``
        / ``Control_Watermark`` frames, replies stamped with the replay
        watermark. Returns the dialable read endpoint."""
        if self._read_server is None:
            self._read_server = ReplicaReadServer(self, endpoint)
        return self._read_server.endpoint

    @property
    def read_endpoint(self) -> Optional[str]:
        return (self._read_server.endpoint
                if self._read_server is not None else None)

    def lag_records(self) -> int:
        """Records the replica's APPLIED state trails the primary's
        advertised append watermark by (0 when fully caught up)."""
        if self.applied_watermark < 0 or self.primary_watermark < 0:
            return 0
        return max(0, self.primary_watermark - self.applied_watermark)

    def release_tail(self) -> None:
        """Apply the records ``hold_tail`` buffered (test/ops seam)."""
        self.hold_tail.clear()
        held, self._held = self._held, []
        for msg in held:
            self._apply(msg)

    def wait_failover(self, timeout: Optional[float] = None) -> str:
        """Block until takeover; returns the bound service endpoint."""
        if not self.took_over.wait(timeout):
            raise TimeoutError("standby: no failover within the timeout "
                               "(primary still alive?)")
        return self.endpoint

    # -- replication stream --------------------------------------------------
    def _send_subscribe(self) -> None:
        # from here until the transfer reply lands, records buffer in
        # _pretransfer (the snapshot may or may not contain them; the
        # reply's watermark decides what replays — _load_state)
        self._awaiting_transfer = True
        self._net.send(Message(src=-1, dst=0,
                               type=MsgType.Control_Replicate,
                               msg_id=next_msg_id()))

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self._net.recv()
            except ConnectionError:
                if self._stop.is_set():
                    return
                self._awaiting_transfer = False
                self._pretransfer.clear()
                self._resubscribe()
                continue
            if msg is None:
                return
            self._detector.beat(_PRIMARY)
            self.last_contact = time.monotonic()
            try:
                if msg.type == MsgType.Control_Wal_Record:
                    self._on_record(msg)
                elif msg.type == MsgType.Control_Reply_Replicate:
                    self._awaiting_transfer = False
                    self._load_state(wire.decode(msg.data))
                elif msg.type == MsgType.Control_Heartbeat:
                    # heartbeats advertise the primary's append
                    # watermark: the lag estimate stays honest while
                    # the WAL idles
                    if msg.watermark > self.primary_watermark:
                        self.primary_watermark = msg.watermark
                        self._lag_gauges()
                elif msg.type == MsgType.Reply_Error:
                    log.error("standby: primary refused replication: %s",
                              wire.decode(msg.data) if msg.data else "?")
            except Exception as exc:  # noqa: BLE001 — a dead pump thread
                # stops lease renewal and fakes a primary death; recover
                # by resubscribing (full transfer) instead of dying
                log.error("standby: pump failed on %s (%r) — "
                          "resubscribing", msg.type, exc)
                try:
                    self._send_subscribe()
                except OSError:
                    pass  # conn dying; the ConnectionError path redials

    def _on_record(self, msg: Message) -> None:
        """One replicated record: advance the primary-side watermarks,
        then apply — or buffer it while a state transfer is pending (the
        transfer's snapshot may already contain it; applying it now
        would be wiped by the snapshot load)."""
        seq = int(msg.watermark)
        if seq > self.primary_watermark:
            self.primary_watermark = seq
            self._lag_gauges()
        if self._awaiting_transfer or self.received_watermark < 0:
            self._pretransfer.append(msg)
            return
        self._accept_record(msg)

    def _accept_record(self, msg: Message) -> None:
        """Gap-check and apply one post-transfer record (or buffer it
        under a held tail)."""
        seq = int(msg.watermark)
        if seq >= 0 and self.received_watermark >= 0:
            if seq <= self.received_watermark:
                return  # duplicate (chaos dup action): already applied
            if seq != self.received_watermark + 1:
                # a record vanished from the stream (chaos drop): the
                # local copy has a hole — resubscribe for a fresh
                # transfer rather than silently under-reporting the lag
                count("REPLICA_GAP_RESYNCS")
                log.error("standby: replication gap (have %d, got %d) — "
                          "resubscribing for a full state transfer",
                          self.received_watermark, seq)
                self._held.clear()
                self._pretransfer.clear()
                self._awaiting_transfer = True
                try:
                    self._send_subscribe()
                except OSError:
                    pass  # conn is dying; _resubscribe redials
                return
        self.received_watermark = max(self.received_watermark, seq)
        if self.hold_tail.is_set():
            self._held.append(msg)
            return
        self._apply(msg)

    def _resubscribe(self) -> None:
        """Connection loss: redial while the lease is still live. Success
        triggers a fresh full-state transfer — records missed during the
        blip are covered by the new snapshot."""
        bo = Backoff(base=0.2, cap=2.0, cancel=self._stop)
        while not self._detector.is_evicted(_PRIMARY):
            if not bo.wait():
                return  # _stop fired mid-sleep
            # re-check after the sleep: _failover sets _stop BEFORE binding
            # the service endpoint, so this cannot redial our own takeover
            # server and subscribe a stream nobody will ever read
            if self._stop.is_set() or self._detector.is_evicted(_PRIMARY):
                return
            try:
                self._send_subscribe()  # _socket_for redials lazily
                log.info("standby: replication stream re-established")
                return
            except OSError:
                continue

    def _run(self, fn):
        """Apply on the dispatcher thread, serialized with any local
        traffic (the standby's tables are normally quiet, but the seam is
        the same one checkpoint restore uses)."""
        server = self._zoo.server
        if server is None or not hasattr(server, "run_serialized"):
            return fn()
        return server.run_serialized(fn)

    def _load_state(self, payload: Any) -> None:
        tables = payload.get("tables", {})
        dedup = payload.get("dedup", [])
        watermark = int(payload.get("watermark", -1))

        def run():
            for table_id, blob in tables.items():
                server_table = self._tables.get(int(table_id))
                if server_table is None:
                    log.error("standby: state transfer names unknown table "
                              "%s — create tables in the primary's order",
                              table_id)
                    continue
                data = bytes(np.ascontiguousarray(
                    np.asarray(blob, dtype=np.uint8)))
                server_table.load(mv_io.MemoryStream(data))
            # the transfer IS the state at `watermark`: adopt it as both
            # the received and applied position inside the serialized
            # block, so a read serialized behind us sees them together
            self.applied_watermark = watermark
            self.received_watermark = watermark

        self._run(run)
        self._held.clear()
        if watermark > self.primary_watermark:
            self.primary_watermark = watermark
        self._seeds = [tuple(int(x) for x in entry) for entry in dedup]
        # records that raced the transfer onto the wire: replay the
        # suffix the snapshot does NOT contain (seq > watermark), in
        # order; the rest were already in the snapshot
        backlog = sorted(self._pretransfer,
                         key=lambda m: int(m.watermark))
        self._pretransfer = []
        self._lag_gauges()
        self.synced.set()
        log.info("standby: state transfer complete (%d table(s), %d dedup "
                 "seed(s), watermark %d, %d raced record(s))", len(tables),
                 len(self._seeds), watermark, len(backlog))
        for msg in backlog:
            if int(msg.watermark) > watermark:
                self._accept_record(msg)

    def _apply(self, msg: Message) -> None:
        server_table = self._tables.get(msg.table_id)
        if server_table is None:
            log.error("standby: WAL record for unknown table %d dropped",
                      msg.table_id)
            return
        request = wire.decode(msg.data)
        seq = int(msg.watermark)

        def run():
            server_table.process_add(request)
            if seq >= 0:
                # advanced inside the serialized block: a read serialized
                # behind this apply observes state and watermark together
                self.applied_watermark = seq

        self._run(run)
        self._seeds.append((msg.req_id, msg.src, msg.msg_id))
        self.records_applied += 1
        self._lag_gauges()

    def _lag_gauges(self) -> None:
        """REPLICA_WATERMARK / REPLICA_LAG_RECORDS — the replay-lag
        telemetry the slot-free stats RPC serves (docs/observability.md).
        A replica that knows its shard (metrics_shard identity) also
        publishes the shard-labeled twin, so a merged stats fan-out (and
        the Prometheus exposition) reads per-shard pressure without
        joining on endpoint lists."""
        lag = self.lag_records()
        gauge_set("REPLICA_WATERMARK", max(self.applied_watermark, 0))
        gauge_set("REPLICA_LAG_RECORDS", lag)
        try:
            shard = int(config.get_flag("metrics_shard"))
        except Exception:  # noqa: BLE001 — gauge before flag definition
            shard = -1
        if shard >= 0:
            gauge_set(f"REPLICA_SHARD{shard}_LAG_RECORDS", lag)

    # -- failover ------------------------------------------------------------
    def _alive_probe(self) -> bool:
        """Can the primary still accept a TCP connection? The guard
        against FALSE lease expiry: on an oversubscribed host the pump
        thread can starve past the lease while the primary is perfectly
        healthy — taking over then would bind against a live primary and
        fork the service. A genuinely dead primary refuses instantly."""
        import socket as socket_mod
        host, port = self._primary_endpoint.rsplit(":", 1)
        try:
            probe = socket_mod.create_connection(
                (host, int(port)),
                timeout=max(0.5, (self._detector.lease_seconds or 1.0) / 2))
            probe.close()
            return True
        except OSError:
            return False

    # a wedged-but-accepting primary must still fail over eventually:
    # the probe may veto at most this many consecutive lease expiries
    _MAX_PROBE_VETOES = 3

    def _watch(self) -> None:
        period = max(0.05, (self._detector.lease_seconds or 1.0) / 4.0)
        vetoes = 0
        while not self._stop.wait(period):
            if _PRIMARY not in self._detector.reap():
                vetoes = 0  # lease healthy again: stall passed
            else:
                if (vetoes < self._MAX_PROBE_VETOES
                        and self._alive_probe()):
                    vetoes += 1
                    count("STANDBY_FALSE_LEASE_EXPIRY")
                    log.error("standby: lease expired but the primary at "
                              "%s still accepts connections — re-arming "
                              "the lease (%d/%d; scheduling stall, not "
                              "death)", self._primary_endpoint, vetoes,
                              self._MAX_PROBE_VETOES)
                    self._detector.register(_PRIMARY)
                    # the stream itself may be half-dead even though the
                    # primary accepts: a fresh subscribe either refreshes
                    # the state (harmless duplicate on a live stream) or
                    # fails and kicks the dial-level reconnect machinery
                    try:
                        self._send_subscribe()
                    except OSError:
                        pass  # the pump's conn-drop path takes it from here
                    continue
                if self.takeover:
                    self._failover()
                else:
                    # pure read replica: nobody races to bind the dead
                    # primary's endpoint. The lag is unbounded from here,
                    # so budget-bound reads refuse (unbounded-staleness
                    # reads keep serving the last-known state).
                    self.primary_dead = True
                    count("REPLICA_PRIMARY_LOST")
                    log.error("replica: primary lease expired after %d "
                              "replicated record(s) — serving reads with "
                              "UNBOUNDED staleness only", self.records_applied)
                return

    def _failover(self) -> None:
        import multiverso_tpu as mv
        log.info("standby: primary lease expired after %d replicated "
                 "record(s) — taking over %s", self.records_applied,
                 self._service_endpoint)
        count("FAILOVERS")
        if self._read_server is not None:
            # the replica is becoming the primary: its read listener (and
            # the replay watermark it stamps) retires with the role —
            # read clients fall back / re-route on the connection loss
            self._read_server.stop()
            self._read_server = None
        # post-mortem before state changes hands: what was in flight and
        # what the dashboard looked like when the primary's lease expired
        flight_dump("standby_failover", primary=self._primary_endpoint,
                    records_applied=self.records_applied)
        self._stop.set()
        self._net.finalize()
        self._zoo._dedup_seeds = list(self._seeds)
        # the dead primary's port can linger for a beat while the kernel
        # tears the old socket down — retry the bind briefly
        bo = Backoff(base=0.2, cap=1.0, deadline=time.monotonic() + 15.0)
        while True:
            try:
                self.endpoint = mv.serve(self._service_endpoint)
                break
            except OSError as exc:
                if not bo.wait():
                    log.error("standby: could not bind %s after failover: "
                              "%r", self._service_endpoint, exc)
                    raise
        self.took_over.set()
        log.info("standby: serving on %s — clients resume via their "
                 "reconnect path", self.endpoint)


class ReplicaReadServer:
    """The replica's slot-free read listener (docs/serving.md).

    Answers exactly eight frame types — ``Request_Read`` (a watermark-
    stamped Get, admission-checked against the request's staleness
    budget), ``Request_Query`` (slot-free top-k retrieval pushdown,
    admission-checked exactly like a Read), ``Control_Watermark``,
    ``Control_Stats``, ``Control_Traces``, ``Control_Profile``,
    ``Control_Digest`` (the fleet auditor's state-digest probe,
    obs/audit.py) and heartbeats — and refuses everything else
    loudly: a replica is not a write target, and a misdirected Add must
    fail visibly rather than fork state.
    Reads run through the standby's dispatcher-serialized seam, so they
    interleave cleanly with the replay applies and the watermark each
    reply carries is exact for the state it observed."""

    def __init__(self, standby: WarmStandby,
                 endpoint: str = "127.0.0.1:0") -> None:
        # registers the wire_compression flag (defined at remote's import)
        from multiverso_tpu.runtime import remote as _remote  # noqa: F401
        self._standby = standby
        self._net = make_net()
        self.endpoint = self._net.bind(0, endpoint)
        if not str(config.get_flag("metrics_role")):
            # serving reads makes this process a replica in the fleet's
            # labeled metrics (unless a launcher already stamped a role)
            config.set_flag("metrics_role", "replica")
        self._compress = bool(config.get_flag("wire_compression"))
        hb = float(config.get_flag("heartbeat_seconds"))
        # freshness window: with heartbeats on, a replica that has heard
        # NOTHING from its primary for this long cannot bound its lag
        # (records may be piling up behind a partition) — budget reads
        # refuse until contact resumes
        self._fresh_window = max(3.0 * hb, 1.0) if hb > 0 else 0.0
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="mv-replica-reads")
        self._thread.start()
        log.info("replica: serving reads on %s", self.endpoint)

    def stop(self) -> None:
        self._net.finalize()
        self._thread.join(timeout=10)

    # -- pump ----------------------------------------------------------------
    def _pump(self) -> None:
        while True:
            try:
                msg = self._net.recv()
            except ConnectionError:
                continue  # a read client went away; nothing to clean up
            if msg is None:
                return
            try:
                self._handle(msg)
            except Exception as exc:  # noqa: BLE001 — keep serving
                log.error("replica: error on %s: %r", msg.type, exc)
                self._reply_error(msg, repr(exc))

    def _handle(self, msg: Message) -> None:
        if msg.type == MsgType.Control_Heartbeat:
            return
        if msg.type == MsgType.Request_Read:
            self._serve_read(msg)
        elif msg.type == MsgType.Request_Query:
            self._serve_query(msg)
        elif msg.type == MsgType.Control_Watermark:
            self._reply_watermark(msg)
        elif msg.type == MsgType.Control_Stats:
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Control_Reply_Stats,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode(Dashboard.snapshot())))
        elif msg.type == MsgType.Control_Traces:
            from multiverso_tpu.obs.trace import TRACES
            n = max(1, int(config.get_flag("trace_export_max")))
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Control_Reply_Traces,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode({"role": "replica",
                                  "endpoint": self.endpoint or "",
                                  "t_reply_ns": time.time_ns(),
                                  "traces": TRACES.export(n)})))
        elif msg.type == MsgType.Control_Profile:
            from multiverso_tpu.obs.profiler import PROFILER
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Control_Reply_Profile,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode({"role": "replica",
                                  "endpoint": self.endpoint or "",
                                  "t_reply_ns": time.time_ns(),
                                  "profile": PROFILER.report()})))
        elif msg.type == MsgType.Control_Digest:
            self._reply_digest(msg)
        else:
            self._reply_error(msg, f"replica serves reads only (got "
                                   f"{msg.type.name}); writes go to the "
                                   "primary")

    # -- read path -----------------------------------------------------------
    @slot_free
    def _refusal(self, budget: int) -> Optional[str]:
        """Why this replica may NOT answer a read with staleness budget
        ``budget`` right now (None = admitted). Budget < 0 is unbounded:
        any synced replica answers."""
        s = self._standby
        if s.applied_watermark < 0:
            return "replica-refused: not yet synced with its primary"
        if budget < 0:
            return None
        if s.primary_dead:
            return ("replica-refused: primary lease expired — staleness "
                    "is unbounded")
        lag = s.lag_records()
        if lag > budget:
            return (f"replica-refused: replay lag {lag} records exceeds "
                    f"the staleness budget {budget}")
        if (self._fresh_window
                and time.monotonic() - s.last_contact > self._fresh_window):
            return ("replica-refused: no primary contact within the "
                    "freshness window — lag cannot be bounded")
        return None

    @slot_free
    def _serve_read(self, msg: Message) -> None:
        if 0.0 < msg.deadline < time.monotonic():
            # the caller's budget is gone: serving would burn a replay-
            # serialized gather on an answer nobody is waiting for
            count("DEADLINE_EXPIRED_DROPS")
            self._reply_error(msg, "deadline_exceeded: read expired "
                                   "before the replica served it")
            return
        refusal = self._refusal(int(msg.watermark))
        if refusal is not None:
            count("REPLICA_READ_REFUSALS")
            self._reply_error(msg, refusal)
            return
        server_table = self._standby._tables.get(msg.table_id)
        if server_table is None:
            self._reply_error(msg, f"replica has no table {msg.table_id}")
            return
        request = wire.decode(msg.data)
        hop(msg.req_id, "replica_serve_read")

        def run():
            # state + watermark observed atomically w.r.t. replay applies
            return (server_table.process_get(request),
                    self._standby.applied_watermark)

        result, watermark = self._standby._run(run)
        count("READS_SERVED_REPLICA")
        hop(msg.req_id, "replica_read_reply_sent")
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Reply_Read,
            table_id=msg.table_id, msg_id=msg.msg_id, req_id=msg.req_id,
            trace=msg.trace, watermark=int(watermark),
            data=wire.encode(result, compress=self._compress)))

    @slot_free
    def _serve_query(self, msg: Message) -> None:
        """Request_Query on a replica: the same admission gate as a
        Request_Read (deadline, staleness budget vs replay lag), then
        the top-k scan runs under the replay-serialized seam so the
        watermark stamped on the Reply_Query names exactly the state
        the scan observed. Cold-tier scans never promote rows — a
        replica's tier residency must track its primary's, not its
        query traffic."""
        if 0.0 < msg.deadline < time.monotonic():
            count("DEADLINE_EXPIRED_DROPS")
            self._reply_error(msg, "deadline_exceeded: query expired "
                                   "before the replica served it")
            return
        refusal = self._refusal(int(msg.watermark))
        if refusal is not None:
            count("REPLICA_READ_REFUSALS")
            self._reply_error(msg, refusal)
            return
        server_table = self._standby._tables.get(msg.table_id)
        if server_table is None:
            self._reply_error(msg, f"replica has no table {msg.table_id}")
            return
        from multiverso_tpu.query import query_table
        request = wire.decode(msg.data)
        hop(msg.req_id, "replica_serve_query")

        def run():
            return (query_table(server_table, request),
                    self._standby.applied_watermark)

        result, watermark = self._standby._run(run)
        count("QUERIES_SERVED_REPLICA")
        hop(msg.req_id, "replica_query_reply_sent")
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Reply_Query,
            table_id=msg.table_id, msg_id=msg.msg_id, req_id=msg.req_id,
            trace=msg.trace, watermark=int(watermark),
            data=wire.encode(result, compress=self._compress)))

    @slot_free
    def _reply_watermark(self, msg: Message) -> None:
        s = self._standby
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Watermark,
            msg_id=msg.msg_id, req_id=msg.req_id,
            watermark=s.applied_watermark,
            data=wire.encode({"role": "replica",
                              "watermark": s.applied_watermark,
                              "primary_watermark": s.primary_watermark,
                              "lag": s.lag_records(),
                              "primary_dead": bool(s.primary_dead)})))

    @slot_free
    def _reply_digest(self, msg: Message) -> None:
        """Control_Digest: per-table content digests at this replica's
        EXACT applied watermark — computed under the replay-serialized
        seam, so the (digest, watermark) pair names one precise state.
        The fleet auditor compares it against the primary's digest at
        the same watermark; a mismatch is real divergence, not skew."""
        from multiverso_tpu.obs.audit import digest_payload
        s = self._standby
        t0 = time.perf_counter()

        def run():
            return digest_payload(
                s._tables, role="replica", endpoint=self.endpoint or "",
                watermark=int(s.applied_watermark), layout_version=-1)

        payload = s._run(run)
        observe("AUDIT_DIGEST_SECONDS", time.perf_counter() - t0)
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Digest,
            msg_id=msg.msg_id, req_id=msg.req_id,
            watermark=int(payload.get("watermark", -1)),
            data=wire.encode(payload)))

    @slot_free
    def _reply_error(self, msg: Message, text: str) -> None:
        try:
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Reply_Error,
                table_id=msg.table_id, msg_id=msg.msg_id, req_id=msg.req_id,
                watermark=self._standby.applied_watermark,
                data=wire.encode(text)))
        except OSError:
            pass  # probing client already gone
