"""Warm-standby failover: a second process that tails the primary's WAL.

Dean et al. (NIPS 2012) motivate the warm replica: async training at real
traffic cannot afford a cold restart — the replacement must already hold
the tables when the primary dies. :class:`WarmStandby` delivers that on
the existing wire machinery:

1. **Subscribe** — dial the primary and send ``Control_Replicate``; the
   reply is a quiesced full-state transfer (every table's checkpoint
   bytes + the Add half of the req-id dedup window).
2. **Tail** — the primary forwards every durable WAL append as a
   ``Control_Wal_Record`` frame; the standby applies it to its own tables
   on its dispatcher thread and accumulates the ``(req_id, worker,
   msg_id)`` seeds. Because the primary writes the replication frame
   before the client's ACK frame, an acknowledged Add is always on the
   standby's socket before the primary can die.
3. **Detect** — the primary's liveness rides a lease
   (:class:`~multiverso_tpu.fault.detector.LivenessDetector`): every
   record or heartbeat renews it; on connection loss the standby
   re-subscribes (full state transfer again — cheap insurance against a
   blip) while the lease keeps ticking.
4. **Take over** — when the lease expires, the standby binds the service
   endpoint (``mv.serve``) with its accumulated dedup seeds. Existing
   client retry/reconnect logic resumes against it transparently: resume
   claims are granted (fresh lease table), in-flight Adds retransmit, and
   the seeded dedup window keeps every replayed Add exactly-once.

The service endpoint must be one the clients can re-dial — same host:port
(this module's tests), a VIP, or DNS that fails over with the role.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu import io as mv_io
from multiverso_tpu.dashboard import count
from multiverso_tpu.fault.detector import LivenessDetector
from multiverso_tpu.obs.trace import flight_dump
from multiverso_tpu.fault.inject import make_net
from multiverso_tpu.runtime import wire
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id

_PRIMARY = 0  # the lease id the primary is tracked under


class WarmStandby:
    """Replicates a serving primary and takes over its endpoint on death.

    Construct AFTER ``mv.init`` + ``mv.create_table`` (same flags and
    table order as the primary, so table ids and worker-slot arithmetic
    line up), then ``start()``. ``wait_failover()`` blocks until takeover;
    ``stop()`` abandons the standby role cleanly.
    """

    def __init__(self, primary_endpoint: str, service_endpoint: str,
                 tables: Optional[List[Any]] = None,
                 lease_seconds: Optional[float] = None) -> None:
        from multiverso_tpu.runtime.zoo import Zoo
        self._zoo = Zoo.instance()
        if not self._zoo.started or self._zoo.server is None:
            log.fatal("WarmStandby: init() the PS runtime first")
        self._primary_endpoint = primary_endpoint
        self._service_endpoint = service_endpoint
        source = tables if tables is not None else self._zoo._worker_tables
        self._tables: Dict[int, Any] = {}
        for table in source:
            server_table = getattr(table, "_server_table", table)
            self._tables[int(getattr(server_table, "table_id", 0))] = \
                server_table
        self._detector = LivenessDetector(
            float(lease_seconds if lease_seconds is not None
                  else config.get_flag("lease_seconds")))
        self._seeds: List[Tuple[int, int, int]] = []
        self.records_applied = 0
        self.endpoint: Optional[str] = None
        self.took_over = threading.Event()
        self.synced = threading.Event()
        self._stop = threading.Event()
        self._net = None
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "WarmStandby":
        self._net = make_net()
        self._net.rank = -1
        self._net.connect([self._primary_endpoint])
        self._send_subscribe()  # raises if the primary is unreachable now
        self._detector.register(_PRIMARY)
        for name, target in (("mv-standby-pump", self._pump),
                             ("mv-standby-watch", self._watch)):
            thread = threading.Thread(target=target, daemon=True, name=name)
            thread.start()
            self._threads.append(thread)
        return self

    def stop(self) -> None:
        """Abandon the standby role (no takeover)."""
        self._stop.set()
        if self._net is not None:
            self._net.finalize()
        for thread in self._threads:
            thread.join(timeout=10)
        self._threads.clear()

    def wait_failover(self, timeout: Optional[float] = None) -> str:
        """Block until takeover; returns the bound service endpoint."""
        if not self.took_over.wait(timeout):
            raise TimeoutError("standby: no failover within the timeout "
                               "(primary still alive?)")
        return self.endpoint

    # -- replication stream --------------------------------------------------
    def _send_subscribe(self) -> None:
        self._net.send(Message(src=-1, dst=0,
                               type=MsgType.Control_Replicate,
                               msg_id=next_msg_id()))

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self._net.recv()
            except ConnectionError:
                if self._stop.is_set():
                    return
                self._resubscribe()
                continue
            if msg is None:
                return
            self._detector.beat(_PRIMARY)
            if msg.type == MsgType.Control_Wal_Record:
                self._apply(msg)
            elif msg.type == MsgType.Control_Reply_Replicate:
                self._load_state(wire.decode(msg.data))
            elif msg.type == MsgType.Control_Heartbeat:
                pass
            elif msg.type == MsgType.Reply_Error:
                log.error("standby: primary refused replication: %s",
                          wire.decode(msg.data) if msg.data else "?")

    def _resubscribe(self) -> None:
        """Connection loss: redial while the lease is still live. Success
        triggers a fresh full-state transfer — records missed during the
        blip are covered by the new snapshot."""
        while (not self._stop.is_set()
               and not self._detector.is_evicted(_PRIMARY)):
            time.sleep(0.2)
            # re-check after the sleep: _failover sets _stop BEFORE binding
            # the service endpoint, so this cannot redial our own takeover
            # server and subscribe a stream nobody will ever read
            if self._stop.is_set() or self._detector.is_evicted(_PRIMARY):
                return
            try:
                self._send_subscribe()  # _socket_for redials lazily
                log.info("standby: replication stream re-established")
                return
            except OSError:
                continue

    def _run(self, fn):
        """Apply on the dispatcher thread, serialized with any local
        traffic (the standby's tables are normally quiet, but the seam is
        the same one checkpoint restore uses)."""
        server = self._zoo.server
        if server is None or not hasattr(server, "run_serialized"):
            return fn()
        return server.run_serialized(fn)

    def _load_state(self, payload: Any) -> None:
        tables = payload.get("tables", {})
        dedup = payload.get("dedup", [])

        def run():
            for table_id, blob in tables.items():
                server_table = self._tables.get(int(table_id))
                if server_table is None:
                    log.error("standby: state transfer names unknown table "
                              "%s — create tables in the primary's order",
                              table_id)
                    continue
                data = bytes(np.ascontiguousarray(
                    np.asarray(blob, dtype=np.uint8)))
                server_table.load(mv_io.MemoryStream(data))

        self._run(run)
        self._seeds = [tuple(int(x) for x in entry) for entry in dedup]
        self.synced.set()
        log.info("standby: state transfer complete (%d table(s), %d dedup "
                 "seed(s))", len(tables), len(self._seeds))

    def _apply(self, msg: Message) -> None:
        server_table = self._tables.get(msg.table_id)
        if server_table is None:
            log.error("standby: WAL record for unknown table %d dropped",
                      msg.table_id)
            return
        request = wire.decode(msg.data)
        self._run(lambda: server_table.process_add(request))
        self._seeds.append((msg.req_id, msg.src, msg.msg_id))
        self.records_applied += 1

    # -- failover ------------------------------------------------------------
    def _watch(self) -> None:
        period = max(0.05, (self._detector.lease_seconds or 1.0) / 4.0)
        while not self._stop.wait(period):
            if _PRIMARY in self._detector.reap():
                self._failover()
                return

    def _failover(self) -> None:
        import multiverso_tpu as mv
        log.info("standby: primary lease expired after %d replicated "
                 "record(s) — taking over %s", self.records_applied,
                 self._service_endpoint)
        count("FAILOVERS")
        # post-mortem before state changes hands: what was in flight and
        # what the dashboard looked like when the primary's lease expired
        flight_dump("standby_failover", primary=self._primary_endpoint,
                    records_applied=self.records_applied)
        self._stop.set()
        self._net.finalize()
        self._zoo._dedup_seeds = list(self._seeds)
        # the dead primary's port can linger for a beat while the kernel
        # tears the old socket down — retry the bind briefly
        deadline = time.monotonic() + 15.0
        while True:
            try:
                self.endpoint = mv.serve(self._service_endpoint)
                break
            except OSError as exc:
                if time.monotonic() >= deadline:
                    log.error("standby: could not bind %s after failover: "
                              "%r", self._service_endpoint, exc)
                    raise
                time.sleep(0.2)
        self.took_over.set()
        log.info("standby: serving on %s — clients resume via their "
                 "reconnect path", self.endpoint)
