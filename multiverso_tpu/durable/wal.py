"""Write-ahead log + exactly-once restart recovery for served tables.

Reference capability (not copied): Li et al. (OSDI'14) make replayable
logging the core of parameter-server fault tolerance — every applied
update is re-derivable from a snapshot plus a replay log. The reference
code base never shipped that layer (its ``Store/Load`` hooks were
point-in-time only); this module is the rebuild's version, riding the
same Stream/FileSystem seam the checkpoint layer uses, so the log lands
on any registered scheme (``file://`` local, ``mvfs://`` remote).

Layout under the durability root (the ``wal_dir`` flag)::

    <root>/MANIFEST                      # {"generation": g, "first_segment": s}
    <root>/gen_<g>/table_<id>.mvckpt     # snapshot generation g
    <root>/wal/seg<SSSSSSSS>.t<id>.mvwal # per-table log segments

Record format (within a segment, after a small segment header)::

    u32 crc32(body) | u32 body_len | body
    body = i64 req_id | i32 worker | i64 msg_id | i32 nblobs | blobs...

Blobs are the Add's RAW wire blobs (runtime/wire.py encoding — sparse /
quantized payloads ride as-is), serialized with the checkpoint array
framing. Appends happen on the dispatcher thread immediately before the
add is applied, so **WAL order equals apply order** and replay reproduces
the table bit-for-bit; the append completes before the ACK leaves, so an
acknowledged Add is always either in the log or in the snapshot.

The MANIFEST is the atomic commit point for compaction: a snapshot
rotates the log, stores every table into a fresh generation directory,
then commits ``{generation, first_segment}`` with a tmp+rename — only
after that are older segments and generations retired. A crash at ANY
point leaves the manifest naming a complete (snapshot, log-suffix) pair.

Recovery (:func:`recover`) loads the manifest generation's snapshot,
replays segments ``>= first_segment`` — truncating at the first
bad-checksum/torn record — and returns the replayed ``(req_id, worker,
msg_id)`` triples so the serving layer can rebuild its idempotent-replay
window: a client retransmitting an Add that was logged before the crash
gets a synthesized ACK instead of a second apply.
"""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu import io as mv_io
from multiverso_tpu.checkpoint import (
    _run_serialized, load_table, read_array, write_array)
from multiverso_tpu.dashboard import count, gauge_set, observe
from multiverso_tpu.obs.profiler import clear_wait, mark_wait
from multiverso_tpu.obs.trace import hop
from multiverso_tpu.runtime.contracts import dispatcher_only

_SEG_MAGIC = b"MVWL"
_SEG_VERSION = 1
_SEG_HDR = struct.Struct("<4sBiq")  # magic, version, table_id, segment
_REC_HDR = struct.Struct("<II")     # crc32(body), body length
_REC_BODY = struct.Struct("<qiqi")  # req_id, worker, msg_id, nblobs
_SEG_NAME = re.compile(r"^seg(\d{8})\.t(\d+)\.mvwal$")
_SYNC_LEVELS = ("none", "batch", "always")


@dataclass
class WalRecord:
    """One logged Add: identity triple + the raw wire blobs."""

    table_id: int
    req_id: int
    worker: int
    msg_id: int
    blobs: List[np.ndarray]


def _encode_record(req_id: int, worker: int, msg_id: int,
                   blobs: List[np.ndarray]) -> bytes:
    buf = mv_io.MemoryStream()
    buf.write(_REC_BODY.pack(req_id, worker, msg_id, len(blobs)))
    for arr in blobs:
        write_array(buf, np.asarray(arr))
    body = buf.getvalue()
    return _REC_HDR.pack(zlib.crc32(body), len(body)) + body


def _read_segment(data: bytes, path: str
                  ) -> Tuple[Optional[List[WalRecord]], int, bool]:
    """Parse one segment's bytes -> (records, valid_byte_length, clean).
    ``records`` is None when the segment header itself is unreadable;
    ``clean`` is False when a torn/bad-checksum tail was cut off."""
    if len(data) < _SEG_HDR.size:
        return None, 0, False
    magic, version, table_id, _segment = _SEG_HDR.unpack_from(data, 0)
    if magic != _SEG_MAGIC or version != _SEG_VERSION:
        log.error("wal: %s has a bad segment header (magic %r v%d)",
                  path, magic, version)
        return None, 0, False
    records: List[WalRecord] = []
    off = _SEG_HDR.size
    while off < len(data):
        if off + _REC_HDR.size > len(data):
            return records, off, False  # torn record header
        crc, blen = _REC_HDR.unpack_from(data, off)
        body = data[off + _REC_HDR.size: off + _REC_HDR.size + blen]
        if len(body) < blen or zlib.crc32(body) != crc:
            return records, off, False  # torn or corrupt body
        req_id, worker, msg_id, nblobs = _REC_BODY.unpack_from(body, 0)
        stream = mv_io.MemoryStream(body)
        stream.seek(_REC_BODY.size)
        blobs = [read_array(stream) for _ in range(nblobs)]
        records.append(WalRecord(table_id, req_id, worker, msg_id, blobs))
        off += _REC_HDR.size + blen
    return records, off, True


# -- manifest -----------------------------------------------------------------

def read_manifest(directory: str) -> Dict[str, int]:
    """The committed recovery point; defaults for a fresh root."""
    fs = mv_io.fs_for(directory)
    path = mv_io.join(directory, "MANIFEST")
    if not fs.exists(path):
        return {"generation": -1, "first_segment": 0}
    with mv_io.get_stream(path, "r") as stream:
        return json.loads(stream.read().decode("utf-8"))


def _write_manifest(directory: str, generation: int,
                    first_segment: int) -> None:
    fs = mv_io.fs_for(directory)
    path = mv_io.join(directory, "MANIFEST")
    tmp = path + ".tmp"
    with mv_io.get_stream(tmp, "w") as stream:
        stream.write(json.dumps({"generation": generation,
                                 "first_segment": first_segment}).encode())
        stream.sync()
    fs.replace(tmp, path)


def _list_segments(fs, wal_dir: str) -> List[Tuple[int, int, str]]:
    """Sorted (segment, table_id, filename) for every segment file."""
    out = []
    for name in fs.listdir(wal_dir):
        match = _SEG_NAME.match(name)
        if match:
            out.append((int(match.group(1)), int(match.group(2)), name))
    return sorted(out)


# -- writer -------------------------------------------------------------------

class WalWriter:
    """Per-table append log under ``<directory>/wal/``.

    ``append`` runs on the dispatcher thread (the caller guarantees it),
    so records within a table are totally ordered with applies; the lock
    only guards against lifecycle calls (rotate/close, observers) from
    other threads. Observers — the standby replication fan-out — see every
    record after it is durable per the sync policy, i.e. the standby never
    holds a record the log could lose.
    """

    def __init__(self, directory: str, sync: Optional[str] = None) -> None:
        self.directory = directory
        self._fs = mv_io.fs_for(directory)
        self._fs.makedirs(directory)
        self.wal_dir = mv_io.join(directory, "wal")
        self._fs.makedirs(self.wal_dir)
        self.sync = (sync if sync is not None
                     else str(config.get_flag("wal_sync"))).strip().lower()
        if self.sync not in _SYNC_LEVELS:
            log.fatal("wal_sync must be one of %s, got %r",
                      "|".join(_SYNC_LEVELS), self.sync)
        manifest = read_manifest(directory)
        self.generation = int(manifest["generation"])
        self.first_segment = int(manifest["first_segment"])
        existing = [seg for seg, _tid, _n in
                    _list_segments(self._fs, self.wal_dir)]
        # resume appending into the highest live segment (restart path)
        self.segment = max(existing) if existing else self.first_segment
        self._streams: Dict[int, mv_io.Stream] = {}
        self._observers: List[Callable] = []
        self._lock = threading.Lock()
        self._closed = False
        # append watermark: records appended by THIS writer, monotonic
        # within the process. The read-replica tier's staleness unit: the
        # primary advertises it on replies/heartbeats, each replicated
        # record carries its own sequence, and a replica's replay
        # watermark is the last sequence it applied (docs/serving.md).
        # Starts at 0 per incarnation — replicas adopt the primary's
        # stamps at subscribe time, and clients treat a watermark
        # REGRESSION (new primary after failover/restart) as a full
        # cache flush, so cross-incarnation continuity is not required.
        self.seq = 0
        # replay debt: bytes appended since the last committed snapshot
        # (restart recovery replays roughly this much). Starts at 0 on a
        # resumed log — the gauge tracks THIS process's contribution.
        self._backlog_bytes = 0

    # -- append path ---------------------------------------------------------
    def _seg_path(self, table_id: int, segment: int) -> str:
        return mv_io.join(self.wal_dir,
                          f"seg{segment:08d}.t{table_id}.mvwal")

    def _stream_for(self, table_id: int) -> mv_io.Stream:
        stream = self._streams.get(table_id)
        if stream is None:
            path = self._seg_path(table_id, self.segment)
            fresh = not self._fs.exists(path)
            stream = mv_io.get_stream(path, "a")
            if not stream.good():
                log.fatal("wal: cannot open segment %s", path)
            if fresh:
                stream.write(_SEG_HDR.pack(_SEG_MAGIC, _SEG_VERSION,
                                           table_id, self.segment))
            self._streams[table_id] = stream
        return stream

    @dispatcher_only
    def append(self, req_id: int, worker: int, table_id: int, msg_id: int,
               blobs: List[np.ndarray]) -> int:
        """Append one record; returns its sequence number (the append
        watermark after this record)."""
        t0 = time.perf_counter()
        record = _encode_record(req_id, worker, msg_id, blobs)
        with self._lock:
            if self._closed:
                log.error("wal: append after close (req %d dropped from "
                          "the log; the table still applies it)", req_id)
                return self.seq
            stream = self._stream_for(table_id)
            stream.write(record)
            if self.sync == "batch":
                stream.flush()
            elif self.sync == "always":
                t_sync = time.perf_counter()
                # profiler wait site: the fsync parks the dispatcher on
                # the disk, the canonical off-CPU wait of durable mode
                _prev_wait = mark_wait("wal_fsync")
                try:
                    stream.sync()
                finally:
                    clear_wait(_prev_wait)
                # the fsync dominates wal_sync=always appends — its own
                # distribution separates disk stalls from encode cost
                observe("WAL_FSYNC_SECONDS", time.perf_counter() - t_sync)
            self._backlog_bytes += len(record)
            self.seq += 1
            seq = self.seq
            observers = list(self._observers)
        count("WAL_APPENDS")
        observe("WAL_APPEND_SECONDS", time.perf_counter() - t0)
        gauge_set("WAL_BACKLOG_BYTES", self._backlog_bytes)
        hop(req_id, "wal_append")
        for observer in observers:
            observer(seq, req_id, worker, table_id, msg_id, blobs)
        return seq

    def add_observer(self, fn: Callable) -> None:
        """``fn(seq, req_id, worker, table_id, msg_id, blobs)`` after each
        durable append — the replication fan-out seam."""
        with self._lock:
            self._observers.append(fn)

    # -- compaction (driven by CheckpointDriver snapshots) -------------------
    def rotate(self) -> int:
        """Close the current segments and start the next; returns the NEW
        segment index — the replay floor for a snapshot taken now."""
        with self._lock:
            self._close_streams()
            self.segment += 1
            return self.segment

    def commit_snapshot(self, generation: int, first_segment: int) -> None:
        """Atomically switch the recovery point to (generation,
        first_segment), then retire everything older. Called only after
        the generation's snapshot files are fully on disk."""
        _write_manifest(self.directory, generation, first_segment)
        old_generation = self.generation
        self.generation = generation
        self.first_segment = first_segment
        retired = 0
        for seg, _tid, name in _list_segments(self._fs, self.wal_dir):
            if seg < first_segment:
                try:
                    self._fs.remove(mv_io.join(self.wal_dir, name))
                    retired += 1
                except OSError as exc:
                    log.error("wal: could not retire %s: %r", name, exc)
        for gen in range(max(0, old_generation), generation):
            self._remove_generation(gen)
        with self._lock:
            self._backlog_bytes = 0
        gauge_set("WAL_BACKLOG_BYTES", 0)
        count("SNAPSHOT_COMPACTIONS")
        log.debug("wal: compacted to generation %d / segment %d "
                  "(%d segment file(s) retired)", generation, first_segment,
                  retired)

    def _remove_generation(self, generation: int) -> None:
        gen_dir = mv_io.join(self.directory, f"gen_{generation}")
        for name in self._fs.listdir(gen_dir):
            try:
                self._fs.remove(mv_io.join(gen_dir, name))
            except OSError:
                pass
        uri = mv_io.URI.parse(gen_dir)
        if uri.scheme == "file":
            try:
                os.rmdir(uri.path)
            except OSError:
                pass

    # -- lifecycle -----------------------------------------------------------
    def _close_streams(self) -> None:
        for stream in self._streams.values():
            try:
                if self.sync != "none":
                    stream.sync()
                stream.close()
            except OSError as exc:
                log.error("wal: segment close failed: %r", exc)
        self._streams.clear()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._close_streams()


# -- recovery -----------------------------------------------------------------

@dataclass
class RecoveryResult:
    """What :func:`recover` did — and the dedup seeds serve() needs."""

    tables_restored: int = 0
    records_replayed: int = 0
    segments_truncated: int = 0
    # replayed (req_id, worker, msg_id) in replay order: the serving
    # layer rebuilds its idempotent-replay window from these
    seeds: List[Tuple[int, int, int]] = field(default_factory=list)


def _truncate_local(path: str, valid: int) -> None:
    """Physically cut a torn/corrupt tail so later tails (standby resync,
    the next recovery) never re-read garbage. Local scheme only — remote
    schemes just stop replaying at the tear."""
    uri = mv_io.URI.parse(path)
    if uri.scheme != "file":
        return
    try:
        with open(uri.path, "r+b") as fp:
            fp.truncate(valid)
    except OSError as exc:
        log.error("wal: could not truncate %s at %d: %r", path, valid, exc)


def recover(tables: List[Any], directory: str) -> RecoveryResult:
    """Exactly-once restart recovery: manifest snapshot + WAL replay.

    Call after the restarted process re-created its tables (same order,
    so table ids match) and BEFORE ``serve()``; pass the returned seeds
    to the serving layer (``mv.durable_recover`` does both). Replay
    applies each record's decoded request directly via ``process_add`` on
    the dispatcher thread, in log order — which equals the original apply
    order — so the recovered table is bit-identical to the pre-crash
    state for every logged Add.
    """
    from multiverso_tpu.runtime import wire

    fs = mv_io.fs_for(directory)
    manifest = read_manifest(directory)
    result = RecoveryResult()
    by_id: Dict[int, Any] = {}
    for table in tables:
        server_table = getattr(table, "_server_table", table)
        by_id[int(getattr(server_table, "table_id", 0))] = server_table

    if manifest["generation"] >= 0:
        gen_dir = mv_io.join(directory, f"gen_{manifest['generation']}")
        for table_id, server_table in by_id.items():
            path = mv_io.join(gen_dir, f"table_{table_id}.mvckpt")
            if fs.exists(path):
                load_table(server_table, path)
                result.tables_restored += 1

    wal_dir = mv_io.join(directory, "wal")
    dead: set = set()  # tables whose log tore mid-history: stop replaying
    for seg, table_id, name in _list_segments(fs, wal_dir):
        if seg < int(manifest["first_segment"]):
            continue  # pre-snapshot leftover; retired at next compaction
        if table_id in dead:
            log.error("wal: skipping %s — an earlier segment of table %d "
                      "was truncated, later records would leave a gap",
                      name, table_id)
            continue
        path = mv_io.join(wal_dir, name)
        with mv_io.get_stream(path, "r") as stream:
            data = stream.read()
        records, valid, clean = _read_segment(data, path)
        if records is None:
            log.error("wal: %s is unreadable — skipped", name)
            dead.add(table_id)
            continue
        if not clean:
            result.segments_truncated += 1
            count("WAL_TRUNCATED_TAIL")
            dead.add(table_id)  # only a final tear is crash-consistent
            _truncate_local(path, valid)
            log.error("wal: %s had a torn/corrupt tail at byte %d — "
                      "truncated, %d record(s) kept", name, valid,
                      len(records))
        server_table = by_id.get(table_id)
        if server_table is None:
            log.error("wal: %s references unknown table %d — skipped "
                      "(tables must be re-created in the original order)",
                      name, table_id)
            continue

        def replay(server_table=server_table, records=records):
            for record in records:
                server_table.process_add(wire.decode(record.blobs))
            return len(records)

        replayed = _run_serialized(replay)
        count("WAL_REPLAYED", replayed)
        result.records_replayed += replayed
        result.seeds.extend((r.req_id, r.worker, r.msg_id) for r in records)
    log.info("durable recovery from %s: %d table(s) restored, %d record(s) "
             "replayed, %d truncated tail(s)", directory,
             result.tables_restored, result.records_replayed,
             result.segments_truncated)
    return result
