"""Liveness: heartbeat/lease tracking for remote workers.

The SSP gate of Ho et al. (NIPS'13) — and BSP before it — is only safe in
production if a dead worker can be evicted from its clock: a crashed peer
otherwise holds every round gate forever. This module is the bookkeeping
half: the RemoteServer registers each remote worker here and renews its
lease on every heartbeat (``Control_Heartbeat``) *and* on every request
frame, so heartbeats only matter while a client idles or blocks. The
recovery half lives in :class:`~multiverso_tpu.runtime.server.SyncServer`:
its stall watchdog calls :meth:`LivenessDetector.reap` each tick and
evicts expired workers from the clock gates on the dispatcher thread.

Local (in-process) workers are never tracked — a thread in this process
cannot silently vanish without taking the server with it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Set


class LivenessDetector:
    """Lease table: worker_id -> last sign of life. ``lease_seconds <= 0``
    disables expiry entirely (registered workers are immortal)."""

    def __init__(self, lease_seconds: float) -> None:
        self.lease_seconds = float(lease_seconds)
        self._last_seen: Dict[int, float] = {}
        self._evicted: Set[int] = set()
        self._lock = threading.Lock()

    # -- lease bookkeeping ---------------------------------------------------
    def register(self, worker_id: int) -> None:
        with self._lock:
            self._last_seen[worker_id] = time.monotonic()

    def beat(self, worker_id: int) -> None:
        """Renew a lease; unknown ids are ignored (a stale frame from a
        deregistered or evicted worker must not resurrect its lease)."""
        with self._lock:
            if worker_id in self._last_seen:
                self._last_seen[worker_id] = time.monotonic()

    def forget(self, worker_id: int) -> None:
        """Graceful deregistration: stop tracking without marking evicted."""
        with self._lock:
            self._last_seen.pop(worker_id, None)

    # -- expiry --------------------------------------------------------------
    def reap(self) -> List[int]:
        """Workers whose lease just expired, each reported exactly once
        (moved to the evicted set); the caller performs the actual clock
        eviction. Empty when leases are disabled."""
        if self.lease_seconds <= 0:
            return []
        now = time.monotonic()
        expired: List[int] = []
        with self._lock:
            for wid, last in list(self._last_seen.items()):
                if now - last > self.lease_seconds:
                    del self._last_seen[wid]
                    self._evicted.add(wid)
                    expired.append(wid)
        return expired

    def is_evicted(self, worker_id: int) -> bool:
        with self._lock:
            return worker_id in self._evicted

    def tracked(self) -> List[int]:
        with self._lock:
            return sorted(self._last_seen)
