"""Fault injection: a transport proxy that applies a seeded fault schedule.

The correctness tool that makes the rest of the fault subsystem verifiable:
:class:`ChaosNet` wraps :class:`~multiverso_tpu.runtime.net.TcpNet` and
perturbs OUTBOUND frames per a rule list — drop / delay / duplicate /
reorder / one-way partition — predicated on (src, dst, MsgType, table) with
count/probability limiters. Rules are deterministic given ``fault_seed``,
so a chaos run replays exactly.

Spec DSL (the ``fault_spec`` flag; ';'-separated rules, first rule that
FIRES wins, non-firing matches still advance that rule's counter)::

    drop:type=Request_Add,every=3         # every 3rd Add frame vanishes
    dup:type=Reply_Add,first=2            # the first two Add replies send twice
    delay:type=Reply_Get,prob=0.5,seconds=0.2
    reorder:dst=0,after=4                 # hold a frame, release behind the next
    partition:src=1,dst=0                 # one-way: rank 1 can never reach rank 0
    corrupt:type=Request_Add,every=6      # seeded bit-flip in the blob payload
    stall:dst=0,seconds=0.2               # gray failure: drip one frame per 0.2s

Predicates: ``src= dst= table=`` (ints), ``type=`` (MsgType name or int).
Limiters: ``first=N`` (only the first N matches), ``after=N`` (skip the
first N), ``every=N`` (every Nth), ``prob=p`` (seeded coin, applied last).
``delay``/``reorder`` take ``seconds=`` (delay duration / hold fallback).
``stall`` is the slow-but-alive gray failure the breaker/deadline drills
need: matching frames enter a per-connection drip queue that releases ONE
frame every ``seconds=`` — head-of-line blocking included, unlike
``delay`` whose timers run concurrently. The peer stays connected and
correct, just pathologically slow.

Any existing test or bench runs under chaos by setting the flags — the
remote client/server build their transports through :func:`make_net`.
Injected events surface as ``FAULT_INJECTED_<ACTION>`` dashboard counters.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import count
from multiverso_tpu.runtime.message import Message, MsgType
from multiverso_tpu.runtime.net import _HEADER, TcpNet

_ACTIONS = ("drop", "delay", "dup", "reorder", "partition", "corrupt",
            "stall")


@dataclass
class FaultRule:
    """One schedule entry: predicates select frames, limiters select which
    of the matching frames actually suffer the action."""

    action: str
    src: Optional[int] = None
    dst: Optional[int] = None
    type: Optional[MsgType] = None
    table: Optional[int] = None
    first: Optional[int] = None
    after: int = 0
    every: Optional[int] = None
    prob: Optional[float] = None
    seconds: float = 0.05
    seen: int = field(default=0, repr=False)  # matching frames so far

    def matches(self, msg: Message) -> bool:
        if self.src is not None and msg.src != self.src:
            return False
        if self.dst is not None and msg.dst != self.dst:
            return False
        if self.type is not None and msg.type != self.type:
            return False
        if self.table is not None and msg.table_id != self.table:
            return False
        return True

    def applies(self, rng: random.Random) -> bool:
        """Limiter check for the CURRENT match (``seen`` already bumped)."""
        nth = self.seen - self.after
        if nth <= 0:
            return False
        if self.first is not None and nth > self.first:
            return False
        if self.every is not None and nth % self.every != 0:
            return False
        if self.prob is not None and rng.random() >= self.prob:
            return False
        return True


def parse_fault_spec(spec: str) -> List[FaultRule]:
    """Parse the ``fault_spec`` DSL into rules; malformed specs are fatal
    (a silently-ignored chaos schedule would fake a passing chaos run)."""
    rules: List[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        action, _, argstr = part.partition(":")
        action = action.strip()
        if action not in _ACTIONS:
            log.fatal("fault_spec: unknown action %r (want one of %s)",
                      action, "|".join(_ACTIONS))
        rule = FaultRule(action=action)
        for kv in filter(None, (s.strip() for s in argstr.split(","))):
            key, _, raw = kv.partition("=")
            key = key.strip()
            raw = raw.strip()
            if key in ("src", "dst", "table", "first", "after", "every"):
                setattr(rule, key, int(raw))
            elif key == "type":
                rule.type = (MsgType(int(raw)) if raw.lstrip("-").isdigit()
                             else MsgType[raw])
            elif key == "prob":
                rule.prob = float(raw)
            elif key == "seconds":
                rule.seconds = float(raw)
            else:
                log.fatal("fault_spec: unknown key %r in rule %r", key, part)
        rules.append(rule)
    return rules


class FaultInjector:
    """Evaluates the rule list against each outbound frame; the first rule
    that fires decides the frame's fate. Seeded, so probabilistic rules
    replay bit-for-bit across runs."""

    def __init__(self, rules: List[FaultRule], seed: int = 0) -> None:
        self.rules = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def fire(self, msg: Message) -> Optional[FaultRule]:
        with self._lock:
            for rule in self.rules:
                if not rule.matches(msg):
                    continue
                rule.seen += 1
                if rule.applies(self._rng):
                    count(f"FAULT_INJECTED_{rule.action.upper()}")
                    return rule
        return None

    def draw(self, n: int) -> int:
        """Seeded integer in [0, n) — corruption offsets/bit picks come
        from the same rng as the prob= coins, so a corrupt schedule
        replays bit-for-bit."""
        with self._lock:
            return self._rng.randrange(n)


class _Held:
    """A reorder-held frame: released behind the next frame to the same
    destination, or by a timer fallback — whichever comes first."""

    __slots__ = ("send", "released", "lock")

    def __init__(self, send) -> None:
        self.send = send
        self.released = False
        self.lock = threading.Lock()

    def release(self) -> None:
        with self.lock:
            if self.released:
                return
            self.released = True
        try:
            self.send()
        except OSError as exc:
            log.debug("chaos: held frame lost with its connection: %r", exc)


class ChaosNet(TcpNet):
    """TcpNet with the fault schedule applied to every outbound frame —
    both the dialed-send path (``_send``) and the explicit-connection reply
    path (``send_via``), so client requests and server replies are equally
    at risk. Inbound frames are untouched: every network fault is
    observable as a send-side event on one of the two endpoints."""

    def __init__(self, injector: FaultInjector) -> None:
        super().__init__()
        self._injector = injector
        self._held: Dict[object, List[_Held]] = {}
        self._held_lock = threading.Lock()
        # stall drip queues: key -> FIFO of deferred sends; one timer
        # chain per key releases one frame per rule.seconds
        self._stalled: Dict[object, List] = {}
        self._stall_lock = threading.Lock()

    # -- intercepted send paths ---------------------------------------------
    def _send(self, msg: Message, channel: int) -> int:
        sup = super(ChaosNet, self)
        return self._apply(msg, lambda: sup._send(msg, channel),
                           key=("rank", msg.dst),
                           raw=lambda fr: sup._send_raw(msg.dst, fr),
                           channel=channel)

    def send_via(self, conn, msg: Message, channel: int = 0,
                 flush: bool = False) -> int:
        sup = super(ChaosNet, self)
        return self._apply(msg,
                           lambda: sup.send_via(conn, msg, channel, flush),
                           key=("conn", id(conn)),
                           raw=lambda fr: sup._send_via_raw(conn, fr),
                           channel=channel)

    # -- schedule application -----------------------------------------------
    def _apply(self, msg: Message, send, key, raw, channel) -> int:
        self._release_held(key)
        rule = self._injector.fire(msg)
        if rule is None:
            return send()
        if rule.action in ("drop", "partition"):
            log.debug("chaos: %s frame %s->%s %s", rule.action, msg.src,
                      msg.dst, msg.type)
            return 0
        if rule.action == "corrupt":
            # seeded single-bit flip in the frame's blob section; the v3
            # frame CRC detects it receiver-side and the frame is
            # discarded — recovered by retransmit, exactly like a drop.
            # (Blob-less frames — heartbeats — pass through untouched:
            # header corruption would kill the connection, a different
            # failure class already covered by the reconnect path.)
            frame = bytearray(self._frame(msg, channel))
            if len(frame) <= _HEADER.size:
                return send()
            pos = _HEADER.size + self._injector.draw(
                len(frame) - _HEADER.size)
            frame[pos] ^= 1 << self._injector.draw(8)
            log.debug("chaos: corrupt frame %s->%s %s (byte %d)", msg.src,
                      msg.dst, msg.type, pos)
            return raw(bytes(frame))
        if rule.action == "dup":
            n = send()
            send()
            return n
        if rule.action == "delay":
            self._later(rule.seconds, send)
            return 0
        if rule.action == "stall":
            # gray failure: the peer is alive but drips — matching frames
            # queue per-connection and release ONE per rule.seconds, so
            # later stalled frames wait behind earlier ones (head-of-line
            # blocking, the signature a breaker must distinguish from a
            # dead peer)
            log.debug("chaos: stall frame %s->%s %s (%.3fs drip)",
                      msg.src, msg.dst, msg.type, rule.seconds)
            self._stall(key, send, rule.seconds)
            return 0
        # reorder: hold; the next frame to this destination overtakes it
        held = _Held(send)
        with self._held_lock:
            self._held.setdefault(key, []).append(held)
        self._later(rule.seconds, held.release)
        return 0

    def _stall(self, key, send, seconds: float) -> None:
        with self._stall_lock:
            q = self._stalled.setdefault(key, [])
            q.append(send)
            if len(q) > 1:
                return  # a drip chain for this key is already running
        self._later(seconds, lambda: self._drip(key, seconds))

    def _drip(self, key, seconds: float) -> None:
        with self._stall_lock:
            q = self._stalled.get(key)
            if not q:
                return
            send = q.pop(0)
            more = bool(q)
        try:
            send()
        except OSError as exc:
            log.debug("chaos: stalled frame lost with its connection: %r",
                      exc)
        if more:
            self._later(seconds, lambda: self._drip(key, seconds))

    def _release_held(self, key) -> None:
        with self._held_lock:
            backlog = self._held.pop(key, None)
        if backlog:
            # the caller's frame goes out first (it is about to be sent by
            # _apply's fall-through); emit the held ones right behind it
            # from the timer thread so the overtake is real
            self._later(0.0, lambda: [h.release() for h in backlog])

    @staticmethod
    def _later(seconds: float, fn) -> None:
        def run():
            try:
                fn()
            except OSError as exc:
                log.debug("chaos: deferred frame lost: %r", exc)
        timer = threading.Timer(max(seconds, 0.0), run)
        timer.daemon = True
        timer.start()


def corrupt_table_row(table, row: int) -> bool:
    """Flip one byte of ``row``'s APPLIED state in a server table — the
    seeded-divergence half of the audit chaos drills (MV_AUDIT_CORRUPT,
    shard/_child.py). Wire-level ``corrupt`` rules cannot stage this:
    the frame CRC discards a corrupted record before it applies, so it
    degrades to a drop. Real divergence — a bad host, a buggy updater, a
    torn restore — lives in applied state, which is what the fleet
    auditor's digests compare. Call under the owning dispatcher seam
    (``run_serialized`` / ``WarmStandby._run``); returns False when the
    row cannot be located."""
    import numpy as np
    server = getattr(table, "_server_table", table)
    action = "state_corrupt"
    row = int(row)

    def flip(arr: np.ndarray) -> bool:
        view = arr.view(np.uint8).reshape(-1)
        if view.size == 0:
            return False
        view[0] ^= 0x01
        count(f"FAULT_INJECTED_{action.upper()}")
        log.error("chaos: corrupted applied state of table %s row %d "
                  "(drill)", getattr(server, "table_id", "?"), row)
        return True

    z = getattr(server, "_z", None)
    if isinstance(z, dict) and isinstance(z.get(row), np.ndarray):
        return flip(z[row])
    tier = getattr(server, "_tier", None)
    if tier is not None:
        cold = tier.get(row)
        if cold is None:
            return False
        arr = np.array(cold, copy=True)
        ok = flip(arr)
        if ok:
            tier.put(row, arr)
        return ok
    store = getattr(server, "_store", None)
    if isinstance(store, dict):
        value = store.get(row)
        if isinstance(value, np.ndarray):
            return flip(value)
        if value is not None:
            store[row] = (value ^ 1 if isinstance(value, int)
                          else repr(value) + "\x00")
            count(f"FAULT_INJECTED_{action.upper()}")
            return True
        return False
    if isinstance(store, np.ndarray):
        target = store[row] if store.ndim > 1 and row < len(store) else store
        return flip(np.atleast_1d(target))
    return False


def make_net() -> TcpNet:
    """Transport factory keyed on the chaos flags: plain TcpNet normally, a
    ChaosNet under ``fault_spec`` — the seam that lets any test or bench
    run under a seeded fault schedule without code changes."""
    spec = str(config.get_flag("fault_spec"))
    if not spec.strip():
        return TcpNet()
    injector = FaultInjector(parse_fault_spec(spec),
                             seed=int(config.get_flag("fault_seed")))
    log.info("fault injection active: %d rule(s), seed=%d",
             len(injector.rules), config.get_flag("fault_seed"))
    return ChaosNet(injector)
