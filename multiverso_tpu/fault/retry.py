"""Retry policy: exponential backoff with full jitter under a deadline.

The parameter-server literature (Li et al., OSDI'14) makes fault tolerance
hinge on *replayable, idempotent* messages: a sender may retry freely
because the receiver deduplicates. This module is the sender half — the
backoff schedule remote clients use for reconnect-and-resume and for
per-request retransmission (:mod:`multiverso_tpu.runtime.remote`). The
receiver half is the server's req-id dedup window; liveness is
:mod:`multiverso_tpu.fault.detector`.

Jitter is *full* jitter (uniform in [delay/2, delay]) so a herd of clients
orphaned by one server restart does not reconnect in lockstep. The jitter
math itself lives in :mod:`multiverso_tpu.utils.backoff` — one schedule
shared by every retry loop in the stack.

Free retries are only safe while the receiver is healthy. Under sustained
overload they invert: each timed-out request becomes two, and the retry
plane amplifies exactly the load that caused the timeouts. Two governors
bound that amplification:

* :class:`RetryBudget` — a token bucket refilled by *successes*. Every
  retransmit, read hedge, or layout re-fetch spends a token; when the
  success rate collapses the bucket drains and retry pressure decays to
  the refill ratio instead of storming.
* :class:`CircuitBreaker` — consecutive-failure trip wire. Open = stop
  sending: writes fail fast with a truthful error, reads fall back to
  replicas. After ``reset_seconds`` one half-open probe is let through;
  its outcome closes or re-opens the breaker.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Iterator, Optional, Tuple

from multiverso_tpu.dashboard import count, gauge_set
from multiverso_tpu.utils.backoff import full_jitter


class RetryPolicy:
    """Backoff schedule: attempt k (k>=1) sleeps ``min(cap, base*2^(k-1))``
    jittered, attempt 0 runs immediately; the whole sequence stops when
    ``deadline`` seconds have elapsed. ``deadline=0`` yields NO attempts —
    the fail-fast escape hatch."""

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 deadline: float = 30.0,
                 rng: Optional[random.Random] = None) -> None:
        self.base = float(base)
        self.cap = float(cap)
        self.deadline = float(deadline)
        self._rng = rng if rng is not None else random.Random()

    @classmethod
    def from_flags(cls, deadline: Optional[float] = None) -> "RetryPolicy":
        from multiverso_tpu import config
        if deadline is None:
            deadline = float(config.get_flag("reconnect_deadline_seconds"))
        return cls(base=float(config.get_flag("retry_base_seconds")),
                   cap=float(config.get_flag("retry_cap_seconds")),
                   deadline=deadline)

    def backoff(self, attempt: int) -> float:
        """Jittered sleep before attempt ``attempt`` (0 -> no sleep)."""
        return full_jitter(self.base, self.cap, attempt, self._rng)

    def attempts(self) -> Iterator[Tuple[int, float]]:
        """Yield ``(attempt_index, seconds_remaining)`` pairs, sleeping the
        jittered backoff between yields; stops once the deadline passes.
        Callers break out on success."""
        start = time.monotonic()
        attempt = 0
        while True:
            remaining = self.deadline - (time.monotonic() - start)
            if remaining <= 0:
                return
            yield attempt, remaining
            attempt += 1
            delay = self.backoff(attempt)
            remaining = self.deadline - (time.monotonic() - start)
            if remaining <= 0:
                return
            time.sleep(min(delay, remaining))


class RetryBudget:
    """Success-refilled token bucket governing retries on one connection.

    Every first-send is free (it is not a retry); every *extra* send —
    retransmit, read hedge, layout re-fetch — must :meth:`allow` first.
    Successes refill ``ratio`` tokens each, so the steady-state retry rate
    is bounded at ``ratio`` x the success rate: a healthy peer affords
    hedging, a degraded peer sees retry pressure decay instead of doubling
    its queue. Denials are counted (``RETRY_BUDGET_DENIALS``) and the
    caller DEFERS or skips the retry — a denial never fails a request,
    the original flight stays pending.

    ``tokens <= 0`` disables the budget (every retry allowed) — the
    compatibility default; drills and overload-sensitive deployments turn
    it on via the ``retry_budget_tokens`` flag. Thread-safe: client pump,
    maintenance timer, and read scheduler all spend from it.
    """

    def __init__(self, tokens: float = 0.0, ratio: float = 0.1) -> None:
        self.cap = float(tokens)
        self.ratio = float(ratio)
        self._tokens = self.cap
        self._lock = threading.Lock()

    @classmethod
    def from_flags(cls) -> "RetryBudget":
        from multiverso_tpu import config
        return cls(tokens=float(config.get_flag("retry_budget_tokens")),
                   ratio=float(config.get_flag("retry_budget_ratio")))

    @property
    def enabled(self) -> bool:
        return self.cap > 0

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def on_success(self) -> None:
        """A correlated reply arrived: refill ``ratio`` tokens (capped)."""
        if not self.enabled:
            return
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)
            gauge_set("RETRY_BUDGET_TOKENS", self._tokens)

    def allow(self) -> bool:
        """Spend one token for a retry; False (and a counted denial) when
        the bucket is dry."""
        if not self.enabled:
            return True
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                gauge_set("RETRY_BUDGET_TOKENS", self._tokens)
                return True
        count("RETRY_BUDGET_DENIALS")
        return False


class CircuitBreaker:
    """Consecutive-failure breaker for one client->server connection.

    Closed: everything flows, any success resets the failure streak.
    ``failures`` consecutive failures (retransmit timeouts, recovery
    events) trip it open (``BREAKER_TRIPS``, gauge ``BREAKER_OPEN``=1):
    :meth:`allow` returns False so writes fail fast with a truthful
    "circuit open" error and the read tier stops falling back to the
    primary — replicas keep serving. After ``reset_seconds`` ONE
    half-open probe is admitted; its success closes the breaker, its
    failure re-opens the window.

    ``failures <= 0`` disables the breaker entirely (never opens) — the
    compatibility default, enabled via the ``breaker_failures`` flag.
    """

    _CLOSED, _OPEN, _HALF_OPEN = 0, 1, 2

    def __init__(self, failures: int = 0, reset_seconds: float = 5.0) -> None:
        self.threshold = int(failures)
        self.reset_seconds = float(reset_seconds)
        self._state = self._CLOSED
        self._streak = 0
        self._opened_at = 0.0
        self._lock = threading.Lock()

    @classmethod
    def from_flags(cls) -> "CircuitBreaker":
        from multiverso_tpu import config
        return cls(failures=int(config.get_flag("breaker_failures")),
                   reset_seconds=float(config.get_flag("breaker_reset_seconds")))

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    @property
    def is_open(self) -> bool:
        """True while the breaker refuses normal traffic (the half-open
        probe window still reports open — callers that just need a yes/no
        should use :meth:`allow`)."""
        with self._lock:
            return self._state != self._CLOSED

    def record_success(self) -> None:
        with self._lock:
            self._streak = 0
            if self._state != self._CLOSED:
                self._state = self._CLOSED
                gauge_set("BREAKER_OPEN", 0.0)

    def record_failure(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._streak += 1
            if self._state == self._HALF_OPEN:
                # the probe failed — restart the open window
                self._state = self._OPEN
                self._opened_at = time.monotonic()
                return
            if self._state == self._CLOSED and self._streak >= self.threshold:
                self._state = self._OPEN
                self._opened_at = time.monotonic()
                count("BREAKER_TRIPS")
                gauge_set("BREAKER_OPEN", 1.0)

    def allow(self) -> bool:
        """May a request be sent right now? Closed -> yes. Open -> no,
        until ``reset_seconds`` elapse, then exactly one half-open probe
        gets a yes (the caller MUST feed its outcome back via
        record_success/record_failure)."""
        if not self.enabled:
            return True
        with self._lock:
            if self._state == self._CLOSED:
                return True
            if self._state == self._OPEN and \
                    time.monotonic() - self._opened_at >= self.reset_seconds:
                self._state = self._HALF_OPEN
                return True
            return False
