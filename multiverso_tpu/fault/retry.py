"""Retry policy: exponential backoff with full jitter under a deadline.

The parameter-server literature (Li et al., OSDI'14) makes fault tolerance
hinge on *replayable, idempotent* messages: a sender may retry freely
because the receiver deduplicates. This module is the sender half — the
backoff schedule remote clients use for reconnect-and-resume and for
per-request retransmission (:mod:`multiverso_tpu.runtime.remote`). The
receiver half is the server's req-id dedup window; liveness is
:mod:`multiverso_tpu.fault.detector`.

Jitter is *full* jitter (uniform in [delay/2, delay]) so a herd of clients
orphaned by one server restart does not reconnect in lockstep.
"""

from __future__ import annotations

import random
import time
from typing import Iterator, Optional, Tuple


class RetryPolicy:
    """Backoff schedule: attempt k (k>=1) sleeps ``min(cap, base*2^(k-1))``
    jittered, attempt 0 runs immediately; the whole sequence stops when
    ``deadline`` seconds have elapsed. ``deadline=0`` yields NO attempts —
    the fail-fast escape hatch."""

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 deadline: float = 30.0,
                 rng: Optional[random.Random] = None) -> None:
        self.base = float(base)
        self.cap = float(cap)
        self.deadline = float(deadline)
        self._rng = rng if rng is not None else random.Random()

    @classmethod
    def from_flags(cls, deadline: Optional[float] = None) -> "RetryPolicy":
        from multiverso_tpu import config
        if deadline is None:
            deadline = float(config.get_flag("reconnect_deadline_seconds"))
        return cls(base=float(config.get_flag("retry_base_seconds")),
                   cap=float(config.get_flag("retry_cap_seconds")),
                   deadline=deadline)

    def backoff(self, attempt: int) -> float:
        """Jittered sleep before attempt ``attempt`` (0 -> no sleep)."""
        if attempt <= 0:
            return 0.0
        delay = min(self.cap, self.base * (2.0 ** (attempt - 1)))
        return delay * (0.5 + 0.5 * self._rng.random())

    def attempts(self) -> Iterator[Tuple[int, float]]:
        """Yield ``(attempt_index, seconds_remaining)`` pairs, sleeping the
        jittered backoff between yields; stops once the deadline passes.
        Callers break out on success."""
        start = time.monotonic()
        attempt = 0
        while True:
            remaining = self.deadline - (time.monotonic() - start)
            if remaining <= 0:
                return
            yield attempt, remaining
            attempt += 1
            delay = self.backoff(attempt)
            remaining = self.deadline - (time.monotonic() - start)
            if remaining <= 0:
                return
            time.sleep(min(delay, remaining))
