"""Fault subsystem: injection harness, retry/replay, liveness.

Three cooperating parts (see ``docs/fault_tolerance.md`` for the failure
model and cookbook):

* :mod:`multiverso_tpu.fault.inject` — a seeded, rule-based transport
  proxy (drop/delay/dup/reorder/partition) switchable via the
  ``fault_spec``/``fault_seed`` flags, so any test or bench runs under
  chaos. The correctness tool that makes the rest verifiable.
* :mod:`multiverso_tpu.fault.retry` — exponential backoff with jitter and
  deadlines for remote clients; paired with idempotent request ids and the
  server-side dedup window so a retried Add applies exactly once
  (Li et al., OSDI'14: replayable, idempotent messages).
* :mod:`multiverso_tpu.fault.detector` — heartbeat/lease tracking; the
  sync watchdog escalates from logging a stall to EVICTING a worker whose
  lease expired, so BSP/SSP rounds no longer deadlock on a crashed peer
  (the condition under which Ho et al.'s SSP gate is safe in production).
* :mod:`multiverso_tpu.fault.lockcheck` — runtime lock-order sanitizer:
  under ``MV_LOCKCHECK=1`` the threading lock factories are wrapped to
  record the per-thread acquisition graph, report lock-order cycles
  (potential deadlocks) and hold-time outliers, and dump the offending
  stacks through the flight recorder.

Counters (``CLIENT_RETRIES``, ``CLIENT_RECONNECTS``, ``SERVER_DEDUP_HITS``,
``WORKER_EVICTIONS``, ``FAULT_INJECTED_*``) register in the dashboard so
chaos runs are observable.
"""

from multiverso_tpu.fault.detector import LivenessDetector  # noqa: F401
from multiverso_tpu.fault.inject import (  # noqa: F401
    ChaosNet, FaultInjector, FaultRule, make_net, parse_fault_spec)
from multiverso_tpu.fault.retry import RetryPolicy  # noqa: F401
from multiverso_tpu.fault import lockcheck  # noqa: F401
