"""Runtime lock-order sanitizer (``MV_LOCKCHECK=1``).

:func:`enable` replaces the ``threading.Lock`` / ``threading.RLock`` /
``threading.Condition`` factories with checked wrappers that maintain,
per thread, the stack of currently-held locks and, globally, a directed
acquisition graph over lock *instances*: acquiring ``B`` while holding
``A`` inserts the edge ``A -> B``.  A cycle in that graph is a lock-order
inversion — the classic precondition for deadlock (the PR 6 multi-device
rendezvous hang and the PR 7 standby transfer race were both this
shape) — and is reported even when the interleaving that would actually
deadlock never happens on this run.  The sanitizer additionally flags
lock-hold-time outliers (a lock held longer than
``MV_LOCKCHECK_HOLD_SECONDS``, default 10s), which in this codebase
almost always means blocking I/O crept under a registry lock.

Findings are recorded (see :func:`take_findings`) and dumped through the
flight recorder (``lock_order_cycle`` / ``lock_hold_outlier`` events)
with the acquisition stacks of both ends of the offending edge, so a CI
failure ships the evidence.  ``tests/conftest.py`` enables the sanitizer
under ``MV_LOCKCHECK=1`` and fails any test on a fresh cycle.

Design notes / limitations:

- Wrapping happens at the factory, so only locks created *after*
  :func:`enable` are checked.  Module-level locks created at import time
  stay native; the runtime creates its interesting locks per
  server/client instance, which is the bug class this targets.
- Nodes are lock instances (labelled with their creation site), never
  call sites, so two unrelated locks born on the same line cannot alias
  into a false cycle.  Instance ids are monotonic serials, immune to
  ``id()`` reuse after GC.
- The inner primitive is acquired *before* any bookkeeping and released
  *after*, and the graph's own mutex is a native lock, so the sanitizer
  cannot introduce an ordering of its own.
- ``Condition.wait`` releases the underlying (wrapped) mutex through the
  normal ``release``/``acquire`` protocol, so waits neither leak held
  state nor count toward hold time.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

from multiverso_tpu.obs.profiler import clear_wait, mark_wait

_REAL = {
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Condition": threading.Condition,
}

_STACK_DEPTH = 12      # frames kept per acquisition stack
_MAX_EDGES = 100_000   # graph bound; beyond this, new edges are dropped+counted

_enabled = False


def _hold_threshold() -> float:
    try:
        return float(os.environ.get("MV_LOCKCHECK_HOLD_SECONDS", "10.0"))
    except ValueError:
        return 10.0


class _Graph:
    """Global acquisition graph + findings store.  All state is guarded
    by a *native* lock so instrumentation never recurses into itself."""

    def __init__(self) -> None:
        self.mutex = _REAL["Lock"]()
        self.serial = 0
        self.labels: Dict[int, str] = {}            # lock serial -> site
        self.edges: Dict[int, Set[int]] = {}        # a -> {b, ...}
        self.edge_stacks: Dict[Tuple[int, int], Tuple[str, str]] = {}
        self.edge_count = 0
        self.dropped_edges = 0
        self.cycles: List[Dict[str, Any]] = []
        self.outliers: List[Dict[str, Any]] = []
        self.seen_cycles: Set[Tuple[int, ...]] = set()
        self.tls = threading.local()

    def next_serial(self) -> int:
        with self.mutex:
            self.serial += 1
            return self.serial

    def held(self) -> List[Tuple[int, str, float]]:
        """This thread's held-lock stack: (serial, stack_text, t_acquire)."""
        stack = getattr(self.tls, "held", None)
        if stack is None:
            stack = self.tls.held = []
        return stack

    def _path(self, src: int, dst: int) -> Optional[List[int]]:
        """DFS: a path src -> ... -> dst along recorded edges, or None."""
        seen = {src}
        trail = [(src, iter(self.edges.get(src, ())))]
        parents = {src: -1}
        while trail:
            node, it = trail[-1]
            nxt = next(it, None)
            if nxt is None:
                trail.pop()
                continue
            if nxt in seen:
                continue
            parents[nxt] = node
            if nxt == dst:
                path = [dst]
                while path[-1] != src:
                    path.append(parents[path[-1]])
                path.reverse()
                return path
            seen.add(nxt)
            trail.append((nxt, iter(self.edges.get(nxt, ()))))
        return None


_G = _Graph()


def _site() -> str:
    """file:line of the frame that created the lock (best effort)."""
    for entry in reversed(traceback.extract_stack(limit=8)):
        if "lockcheck" not in (entry.filename or ""):
            return "%s:%d" % (entry.filename, entry.lineno or 0)
    return "<unknown>"


def _stack_text() -> str:
    return "".join(traceback.format_stack(limit=_STACK_DEPTH)[:-2])


def _record_edges(serial: int) -> List[Dict[str, Any]]:
    """Insert held->serial edges; return any *new* cycle reports (the
    flight-recorder dump happens outside the graph mutex)."""
    held = _G.held()
    if not held:
        return []
    acq_stack = _stack_text()
    reports: List[Dict[str, Any]] = []
    with _G.mutex:
        for h_serial, h_stack, _t in held:
            if h_serial == serial:
                continue
            dests = _G.edges.setdefault(h_serial, set())
            if serial in dests:
                continue
            if _G.edge_count >= _MAX_EDGES:
                _G.dropped_edges += 1
                continue
            # Does serial already reach h_serial?  Then closing the edge
            # h_serial -> serial completes a cycle.
            path = _G._path(serial, h_serial)
            dests.add(serial)
            _G.edge_count += 1
            _G.edge_stacks[(h_serial, serial)] = (h_stack, acq_stack)
            if path is not None:
                cyc = tuple(sorted(path + [serial]))
                if cyc in _G.seen_cycles:
                    continue
                _G.seen_cycles.add(cyc)
                nodes = path + [serial]
                report = {
                    "kind": "lock_order_cycle",
                    "thread": threading.current_thread().name,
                    "locks": [_G.labels.get(n, "?") for n in nodes],
                    "closing_edge": [_G.labels.get(h_serial, "?"),
                                     _G.labels.get(serial, "?")],
                    "held_stack": h_stack,
                    "acquire_stack": acq_stack,
                }
                _G.cycles.append(report)
                reports.append(report)
    return reports


def _dump(reports: List[Dict[str, Any]]) -> None:
    for report in reports:
        try:
            from multiverso_tpu.obs.trace import flight_dump
            from multiverso_tpu.dashboard import count
            if report["kind"] == "lock_order_cycle":
                count("LOCK_ORDER_CYCLES")
            else:
                count("LOCK_HOLD_OUTLIERS")
            flight_dump(report["kind"], **{
                k: v for k, v in report.items() if k != "kind"})
        except Exception:  # noqa: BLE001 — telemetry must never throw here
            pass


class _CheckedLock:
    """Wrapper over a native Lock/RLock with acquisition-graph hooks."""

    _reentrant = False

    def __init__(self) -> None:
        self._inner = (_REAL["RLock"] if self._reentrant
                       else _REAL["Lock"])()
        self._serial = _G.next_serial()
        self._depth = 0  # owning-thread reentrancy depth (RLock only)
        with _G.mutex:
            _G.labels[self._serial] = _site()

    # -- threading.Lock protocol -------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # profiler wait site: a sampled thread parked here is
            # off-CPU in lock contention, not burning cycles
            prev = mark_wait("lock_acquire")
            try:
                got = self._inner.acquire(blocking, timeout)
            finally:
                clear_wait(prev)
        else:
            got = self._inner.acquire(blocking, timeout)
        if not got:
            return False
        if self._reentrant:
            self._depth += 1
            if self._depth > 1:      # reentrant re-acquire: no new edge
                return True
        reports = _record_edges(self._serial)
        _G.held().append((self._serial, _stack_text(), time.monotonic()))
        if reports:
            _dump(reports)
        return True

    def release(self) -> None:
        if self._reentrant and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        if self._reentrant:
            self._depth = 0
        self._pop_held()
        self._inner.release()

    def _pop_held(self) -> None:
        held = _G.held()
        now = time.monotonic()
        outlier = None
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self._serial:
                _serial, stack, t0 = held.pop(i)
                dt = now - t0
                if dt > _hold_threshold():
                    outlier = {
                        "kind": "lock_hold_outlier",
                        "thread": threading.current_thread().name,
                        "lock": _G.labels.get(self._serial, "?"),
                        "held_seconds": round(dt, 3),
                        "threshold": _hold_threshold(),
                        "acquire_stack": stack,
                    }
                break
        if outlier is not None:
            with _G.mutex:
                _G.outliers.append(outlier)
            _dump([outlier])

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return "<mv-checked %s #%d (%s)>" % (
            "RLock" if self._reentrant else "Lock",
            self._serial, _G.labels.get(self._serial, "?"))


class _CheckedRLock(_CheckedLock):
    _reentrant = True

    # threading.Condition's full protocol.  Without these it falls back
    # to an acquire(0) ownership probe, which is wrong for reentrant
    # locks (the probe succeeds for the owner), so they must exist on
    # any RLock handed to a Condition.
    def _is_owned(self) -> bool:
        return self._inner._is_owned()  # type: ignore[attr-defined]

    def _release_save(self) -> Any:
        # Condition.wait fully releases regardless of reentrancy depth.
        depth = self._depth
        self._depth = 0
        self._pop_held()
        return (depth, self._inner._release_save())  # type: ignore[attr-defined]

    def _acquire_restore(self, saved: Any) -> None:
        depth, inner_state = saved
        self._inner._acquire_restore(inner_state)  # type: ignore[attr-defined]
        self._depth = depth
        reports = _record_edges(self._serial)
        _G.held().append((self._serial, _stack_text(), time.monotonic()))
        if reports:
            _dump(reports)


def _make_lock() -> _CheckedLock:
    return _CheckedLock()


def _make_rlock() -> _CheckedRLock:
    return _CheckedRLock()


def _make_condition(lock: Any = None) -> Any:
    return _REAL["Condition"](lock if lock is not None else _make_rlock())


def enable() -> None:
    """Patch the threading lock factories.  Idempotent."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    threading.Lock = _make_lock            # type: ignore[misc]
    threading.RLock = _make_rlock          # type: ignore[misc]
    threading.Condition = _make_condition  # type: ignore[misc,assignment]


def disable() -> None:
    """Restore native factories (existing wrapped locks keep working)."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    threading.Lock = _REAL["Lock"]            # type: ignore[misc]
    threading.RLock = _REAL["RLock"]          # type: ignore[misc]
    threading.Condition = _REAL["Condition"]  # type: ignore[misc]


def enabled() -> bool:
    return _enabled


def findings() -> List[Dict[str, Any]]:
    """All recorded cycle + hold-time reports (does not clear)."""
    with _G.mutex:
        return list(_G.cycles) + list(_G.outliers)


def take_findings() -> List[Dict[str, Any]]:
    """Pop and return all recorded reports (per-test consumption)."""
    with _G.mutex:
        out = list(_G.cycles) + list(_G.outliers)
        _G.cycles.clear()
        _G.outliers.clear()
        return out


def reset() -> None:
    """Drop the whole graph and all findings (unit-test isolation)."""
    with _G.mutex:
        _G.edges.clear()
        _G.edge_stacks.clear()
        _G.edge_count = 0
        _G.dropped_edges = 0
        _G.cycles.clear()
        _G.outliers.clear()
        _G.seen_cycles.clear()


def report_text() -> str:
    """Human-readable summary of all current findings."""
    out: List[str] = []
    for f in findings():
        if f["kind"] == "lock_order_cycle":
            out.append("LOCK-ORDER CYCLE (thread %s):\n  locks: %s\n"
                       "  closing edge: %s -> %s\n"
                       "--- stack holding first lock ---\n%s"
                       "--- stack acquiring second lock ---\n%s" %
                       (f["thread"], " -> ".join(f["locks"]),
                        f["closing_edge"][0], f["closing_edge"][1],
                        f["held_stack"], f["acquire_stack"]))
        else:
            out.append("LOCK HOLD OUTLIER (thread %s): %s held %.3fs "
                       "(threshold %.3fs)\n--- acquire stack ---\n%s" %
                       (f["thread"], f["lock"], f["held_seconds"],
                        f["threshold"], f["acquire_stack"]))
    return "\n\n".join(out)
