"""URI-dispatched stream IO — capability parity with the reference IO layer.

Reference capability (not copied): ``URI`` parse + ``Stream`` abstraction with
scheme-dispatched factories (``file://`` local stdio stream, ``hdfs://``
libhdfs), plus a ``TextReader`` line reader
(``include/multiverso/io/io.h:24-82``, ``src/io/io.cpp``, ``src/io/local_stream.cpp``).

TPU-era design: the factory is an open registry so cloud schemes (``gs://``
via tensorstore/orbax) can plug in; checkpointing (checkpoint.py) rides this
layer exactly like the reference's ServerTable::Store/Load rides Stream.
"""

from __future__ import annotations

import io as _pyio
import os
from dataclasses import dataclass
from typing import BinaryIO, Callable, Dict, Optional

from multiverso_tpu import log


@dataclass
class URI:
    """Parsed resource locator: ``scheme://host/path`` (scheme defaults to file)."""

    scheme: str
    host: str
    path: str
    raw: str

    @classmethod
    def parse(cls, address: str) -> "URI":
        if "://" not in address:
            return cls(scheme="file", host="", path=address, raw=address)
        scheme, _, rest = address.partition("://")
        if scheme == "file":
            return cls(scheme="file", host="", path=rest or "/", raw=address)
        host, sep, path = rest.partition("/")
        return cls(scheme=scheme, host=host, path=(sep + path) if sep else "", raw=address)


class Stream:
    """Binary stream interface (reference: ``Stream::Write/Read``)."""

    def write(self, data: bytes) -> int:
        raise NotImplementedError

    def read(self, size: int = -1) -> bytes:
        raise NotImplementedError

    def good(self) -> bool:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        """Durability barrier: on return, everything written so far has
        reached stable storage (fsync where the scheme has one). The WAL's
        ``wal_sync=always`` policy rides this; schemes without a real
        barrier degrade to flush()."""
        self.flush()

    def close(self) -> None:
        pass

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LocalStream(Stream):
    """``file://`` stream over host stdio."""

    def __init__(self, path: str, mode: str = "r") -> None:
        binary_mode = mode if "b" in mode else mode + "b"
        if "w" in mode or "a" in mode:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
        self._path = path
        self._fp: Optional[BinaryIO] = None
        try:
            self._fp = open(path, binary_mode)
        except OSError as exc:
            log.error("LocalStream: cannot open %s (%s)", path, exc)

    def write(self, data: bytes) -> int:
        if self._fp is None:
            log.fatal("LocalStream.write on bad stream %s", self._path)
        return self._fp.write(data)

    def read(self, size: int = -1) -> bytes:
        if self._fp is None:
            log.fatal("LocalStream.read on bad stream %s", self._path)
        return self._fp.read(size)

    def good(self) -> bool:
        return self._fp is not None

    def flush(self) -> None:
        if self._fp is not None:
            self._fp.flush()

    def sync(self) -> None:
        if self._fp is not None:
            self._fp.flush()
            os.fsync(self._fp.fileno())

    def close(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None


class MemoryStream(Stream):
    """In-memory stream — used by tests and the wire-format round-trips."""

    def __init__(self, data: bytes = b"") -> None:
        self._buf = _pyio.BytesIO(data)

    def write(self, data: bytes) -> int:
        return self._buf.write(data)

    def read(self, size: int = -1) -> bytes:
        return self._buf.read(size)

    def good(self) -> bool:
        return True

    def seek(self, pos: int) -> None:
        self._buf.seek(pos)

    def getvalue(self) -> bytes:
        return self._buf.getvalue()


_FACTORIES: Dict[str, Callable[[URI, str], Stream]] = {}


def register_scheme(scheme: str, factory: Callable[[URI, str], Stream]) -> None:
    _FACTORIES[scheme] = factory


register_scheme("file", lambda uri, mode: LocalStream(uri.path, mode))


class FsspecStream(Stream):
    """Cloud/object-store schemes (``gs://``, ``s3://``, ``memory://``, …)
    through fsspec when it is importable — the deployment-gated analog of
    the reference's compile-gated ``hdfs://`` (MULTIVERSO_USE_HDFS,
    src/io/hdfs_stream.cpp). Engaged as the fallback factory for any scheme
    fsspec knows; ``gs://`` additionally needs gcsfs + network at use time."""

    def __init__(self, address: str, mode: str) -> None:
        import fsspec  # gated: only reached when installed
        binary_mode = mode if "b" in mode else mode + "b"
        self._fp = None
        try:
            self._fp = fsspec.open(address, binary_mode).open()
        except Exception as exc:  # missing backend, auth, network…
            log.error("FsspecStream: cannot open %s (%s)", address, exc)

    def write(self, data: bytes) -> int:
        if self._fp is None:
            log.fatal("FsspecStream.write on bad stream")
        return self._fp.write(data)

    def read(self, size: int = -1) -> bytes:
        if self._fp is None:
            log.fatal("FsspecStream.read on bad stream")
        return self._fp.read(size)

    def good(self) -> bool:
        return self._fp is not None

    def flush(self) -> None:
        if self._fp is not None:
            self._fp.flush()

    def close(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None


def _fsspec_known_scheme(scheme: str) -> bool:
    try:
        import fsspec
        return scheme in fsspec.available_protocols()
    except Exception:
        return False


def get_stream(address: str, mode: str = "r") -> Stream:
    """StreamFactory::GetStream parity: dispatch on URI scheme; schemes not
    registered explicitly fall back to fsspec when it can handle them."""
    uri = URI.parse(address)
    factory = _FACTORIES.get(uri.scheme)
    if factory is None:
        if _fsspec_known_scheme(uri.scheme):
            return FsspecStream(address, mode)
        log.fatal("Can not support the protocol: %s", uri.scheme)
    return factory(uri, mode)


# -- filesystem operations (directory-level) ---------------------------------
# The checkpoint driver needs more than streams: exists / atomic replace /
# makedirs / listdir on whatever scheme the snapshot directory lives on.

class FileSystem:
    """Directory operations for one scheme (default impl: local files)."""

    def exists(self, address: str) -> bool:
        return os.path.exists(URI.parse(address).path)

    def replace(self, src: str, dst: str) -> None:
        """Atomic rename (the checkpoint commit step)."""
        os.replace(URI.parse(src).path, URI.parse(dst).path)

    def makedirs(self, address: str) -> None:
        os.makedirs(URI.parse(address).path, exist_ok=True)

    def listdir(self, address: str) -> list:
        path = URI.parse(address).path
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def remove(self, address: str) -> None:
        os.remove(URI.parse(address).path)


class FsspecFileSystem(FileSystem):
    """Directory ops for fsspec-served schemes. Note: ``replace`` is a
    move, not an atomic rename — object stores (GCS/S3) have no atomic
    rename; the checkpoint tmp+replace pattern degrades to last-writer-wins
    there, which is the same contract the reference's HDFS path had."""

    def __init__(self, scheme: str) -> None:
        import fsspec
        self._fs = fsspec.filesystem(scheme)

    def exists(self, address: str) -> bool:
        return self._fs.exists(address)

    def replace(self, src: str, dst: str) -> None:
        if self._fs.exists(dst):
            self._fs.rm(dst)
        self._fs.mv(src, dst)

    def makedirs(self, address: str) -> None:
        self._fs.makedirs(address, exist_ok=True)

    def listdir(self, address: str) -> list:
        return sorted(p.rsplit("/", 1)[-1]
                      for p in self._fs.ls(address, detail=False))

    def remove(self, address: str) -> None:
        self._fs.rm(address)


_FILESYSTEMS: Dict[str, FileSystem] = {"file": FileSystem()}


def register_fs(scheme: str, fs: FileSystem) -> None:
    _FILESYSTEMS[scheme] = fs


def fs_for(address: str) -> FileSystem:
    """FileSystem serving the address's scheme; unregistered schemes fall
    back to fsspec when it knows them (matching get_stream's dispatch)."""
    scheme = URI.parse(address).scheme
    fs = _FILESYSTEMS.get(scheme)
    if fs is None:
        if _fsspec_known_scheme(scheme):
            fs = _FILESYSTEMS[scheme] = FsspecFileSystem(scheme)
        else:
            log.fatal("no filesystem registered for: %s", address)
    return fs


def join(address: str, *names: str) -> str:
    """Scheme-preserving path join (addresses always use '/')."""
    base = address.rstrip("/")
    tail = "/".join(n.strip("/") for n in names)
    return f"{base}/{tail}" if tail else base


class TextReader:
    """Buffered line reader over a Stream (reference: ``TextReader::GetLine``)."""

    def __init__(self, address: str, buf_size: int = 1 << 16) -> None:
        self._stream = get_stream(address, "r")
        self._buf_size = buf_size
        self._pending = b""
        self._eof = False

    def get_line(self) -> Optional[str]:
        while True:
            nl = self._pending.find(b"\n")
            if nl >= 0:
                line, self._pending = self._pending[:nl], self._pending[nl + 1:]
                return line.decode("utf-8", errors="replace").rstrip("\r")
            if self._eof:
                if self._pending:
                    line, self._pending = self._pending, b""
                    return line.decode("utf-8", errors="replace").rstrip("\r")
                return None
            chunk = self._stream.read(self._buf_size)
            if not chunk:
                self._eof = True
            else:
                self._pending += chunk

    def close(self) -> None:
        self._stream.close()


# Second storage scheme: socket-served remote filesystem (the hdfs:// analog).
# Imported last — mvfs.py uses the names defined above.
from multiverso_tpu.io import mvfs  # noqa: E402,F401
