"""URI-dispatched stream IO — capability parity with the reference IO layer.

Reference capability (not copied): ``URI`` parse + ``Stream`` abstraction with
scheme-dispatched factories (``file://`` local stdio stream, ``hdfs://``
libhdfs), plus a ``TextReader`` line reader
(``include/multiverso/io/io.h:24-82``, ``src/io/io.cpp``, ``src/io/local_stream.cpp``).

TPU-era design: the factory is an open registry so cloud schemes (``gs://``
via tensorstore/orbax) can plug in; checkpointing (checkpoint.py) rides this
layer exactly like the reference's ServerTable::Store/Load rides Stream.
"""

from __future__ import annotations

import io as _pyio
import os
from dataclasses import dataclass
from typing import BinaryIO, Callable, Dict, Optional

from multiverso_tpu import log


@dataclass
class URI:
    """Parsed resource locator: ``scheme://host/path`` (scheme defaults to file)."""

    scheme: str
    host: str
    path: str
    raw: str

    @classmethod
    def parse(cls, address: str) -> "URI":
        if "://" not in address:
            return cls(scheme="file", host="", path=address, raw=address)
        scheme, _, rest = address.partition("://")
        if scheme == "file":
            return cls(scheme="file", host="", path=rest or "/", raw=address)
        host, sep, path = rest.partition("/")
        return cls(scheme=scheme, host=host, path=(sep + path) if sep else "", raw=address)


class Stream:
    """Binary stream interface (reference: ``Stream::Write/Read``)."""

    def write(self, data: bytes) -> int:
        raise NotImplementedError

    def read(self, size: int = -1) -> bytes:
        raise NotImplementedError

    def good(self) -> bool:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LocalStream(Stream):
    """``file://`` stream over host stdio."""

    def __init__(self, path: str, mode: str = "r") -> None:
        binary_mode = mode if "b" in mode else mode + "b"
        if "w" in mode or "a" in mode:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
        self._path = path
        self._fp: Optional[BinaryIO] = None
        try:
            self._fp = open(path, binary_mode)
        except OSError as exc:
            log.error("LocalStream: cannot open %s (%s)", path, exc)

    def write(self, data: bytes) -> int:
        if self._fp is None:
            log.fatal("LocalStream.write on bad stream %s", self._path)
        return self._fp.write(data)

    def read(self, size: int = -1) -> bytes:
        if self._fp is None:
            log.fatal("LocalStream.read on bad stream %s", self._path)
        return self._fp.read(size)

    def good(self) -> bool:
        return self._fp is not None

    def flush(self) -> None:
        if self._fp is not None:
            self._fp.flush()

    def close(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None


class MemoryStream(Stream):
    """In-memory stream — used by tests and the wire-format round-trips."""

    def __init__(self, data: bytes = b"") -> None:
        self._buf = _pyio.BytesIO(data)

    def write(self, data: bytes) -> int:
        return self._buf.write(data)

    def read(self, size: int = -1) -> bytes:
        return self._buf.read(size)

    def good(self) -> bool:
        return True

    def seek(self, pos: int) -> None:
        self._buf.seek(pos)

    def getvalue(self) -> bytes:
        return self._buf.getvalue()


_FACTORIES: Dict[str, Callable[[URI, str], Stream]] = {}


def register_scheme(scheme: str, factory: Callable[[URI, str], Stream]) -> None:
    _FACTORIES[scheme] = factory


register_scheme("file", lambda uri, mode: LocalStream(uri.path, mode))


def get_stream(address: str, mode: str = "r") -> Stream:
    """StreamFactory::GetStream parity: dispatch on URI scheme."""
    uri = URI.parse(address)
    factory = _FACTORIES.get(uri.scheme)
    if factory is None:
        log.fatal("Can not support the protocol: %s", uri.scheme)
    return factory(uri, mode)


class TextReader:
    """Buffered line reader over a Stream (reference: ``TextReader::GetLine``)."""

    def __init__(self, address: str, buf_size: int = 1 << 16) -> None:
        self._stream = get_stream(address, "r")
        self._buf_size = buf_size
        self._pending = b""
        self._eof = False

    def get_line(self) -> Optional[str]:
        while True:
            nl = self._pending.find(b"\n")
            if nl >= 0:
                line, self._pending = self._pending[:nl], self._pending[nl + 1:]
                return line.decode("utf-8", errors="replace").rstrip("\r")
            if self._eof:
                if self._pending:
                    line, self._pending = self._pending, b""
                    return line.decode("utf-8", errors="replace").rstrip("\r")
                return None
            chunk = self._stream.read(self._buf_size)
            if not chunk:
                self._eof = True
            else:
                self._pending += chunk

    def close(self) -> None:
        self._stream.close()
