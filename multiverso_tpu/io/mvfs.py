"""``mvfs://`` — a socket-served remote filesystem scheme.

Reference capability (not copied): the second registered Stream scheme was
``hdfs://`` — remote storage reached over the network through libhdfs
(``src/io/hdfs_stream.cpp:7-157``), proving ``StreamFactory`` is a real
dispatch seam, compile-gated behind MULTIVERSO_USE_HDFS.

TPU-era design: no HDFS exists in the image (and cloud egress is a
deployment property), so the remote scheme is self-hosted: ``MvfsServer``
exports a local directory over TCP with the same framed length-prefixed
protocol shape the runtime's host wire uses, and ``MvfsStream`` is the
client-side ``Stream``. Writes land in a server-side temp file and commit
with an atomic rename on close — the same crash-safety contract the local
checkpoint driver uses. A ``MvfsFileSystem`` exposes the directory
operations (exists / replace / makedirs / listdir) so ``CheckpointDriver``
can snapshot THROUGH the scheme, not just open streams on it.

Protocol: one request/reply pair per operation. Frame =
``uint32 header_len | header json | uint64 payload_len | payload bytes``.
Ops: open_r, read, open_w, write, close_r/close_w (commit), exists,
replace, makedirs, listdir, remove.

Example::

    server = MvfsServer(root="/data/ckpt")
    endpoint = server.serve("0.0.0.0:0")          # host:port
    # elsewhere (any process with TCP reach):
    with get_stream(f"mvfs://{endpoint}/run1/t0.mvckpt", "w") as s:
        s.write(payload)
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import struct
import threading
from typing import Any, Dict, Optional, Tuple

from multiverso_tpu import log
from multiverso_tpu.io import FileSystem, Stream, URI, register_fs, register_scheme

_HDR = struct.Struct("<I")
_PAY = struct.Struct("<Q")
_tmp_ids = itertools.count()  # unique temp-file suffixes, server-wide


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("mvfs: peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _send(sock: socket.socket, header: Dict[str, Any],
          payload: bytes = b"") -> None:
    head = json.dumps(header).encode()
    sock.sendall(_HDR.pack(len(head)) + head + _PAY.pack(len(payload))
                 + payload)


def _recv(sock: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    (hlen,) = _HDR.unpack(_read_exact(sock, _HDR.size))
    header = json.loads(_read_exact(sock, hlen).decode())
    (plen,) = _PAY.unpack(_read_exact(sock, _PAY.size))
    payload = _read_exact(sock, plen) if plen else b""
    return header, payload


class MvfsServer:
    """Serves a local root directory to remote MvfsStream clients."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._sock: Optional[socket.socket] = None
        self._threads: list = []
        self._live_conns: set = set()
        self._conns_lock = threading.Lock()
        self._active = False
        self.endpoint = ""

    # -- lifecycle -----------------------------------------------------------
    def serve(self, endpoint: str = "127.0.0.1:0") -> str:
        host, _, port = endpoint.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(16)
        self._active = True
        self.endpoint = f"{host or '127.0.0.1'}:{self._sock.getsockname()[1]}"
        accept = threading.Thread(target=self._accept_loop,
                                  name="mvfs-accept", daemon=True)
        accept.start()
        self._threads.append(accept)
        return self.endpoint

    def stop(self) -> None:
        """Take the export offline: stop accepting AND sever established
        connections (a stopped server must not keep mutating the root
        through old sockets)."""
        self._active = False
        if self._sock is not None:
            try:
                # shutdown BEFORE close: a thread blocked in accept() holds
                # the open file description, keeping the port bound after
                # close(); shutdown wakes it so the port actually frees
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        with self._conns_lock:
            live = list(self._live_conns)
        for conn in live:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def __enter__(self) -> "MvfsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals -------------------------------------------------------------
    def _resolve(self, path: str) -> str:
        """Map a request path under the exported root; reject escapes."""
        full = os.path.abspath(os.path.join(self.root, path.lstrip("/")))
        if not (full == self.root or full.startswith(self.root + os.sep)):
            raise PermissionError(f"path escapes export root: {path}")
        return full

    def _accept_loop(self) -> None:
        while self._active and self._sock is not None:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # daemon threads self-terminate on disconnect; not retained (a
            # long-lived server would otherwise grow a dead-Thread list)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="mvfs-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # per-connection open handles: id -> (file object, temp path or None)
        handles: Dict[int, Tuple[Any, Optional[str]]] = {}
        next_id = 0
        with self._conns_lock:
            if not self._active:
                conn.close()
                return
            self._live_conns.add(conn)
        try:
            while True:
                try:
                    req, payload = _recv(conn)
                except (ConnectionError, OSError):
                    return
                try:
                    reply, data = self._handle(req, payload, handles)
                    if "handle_new" in reply:
                        handles[next_id] = reply.pop("handle_new")
                        reply["handle"] = next_id
                        next_id += 1
                except Exception as exc:  # surface as a client-side error
                    reply, data = {"err": f"{type(exc).__name__}: {exc}"}, b""
                _send(conn, reply, data)
        finally:
            for fp, tmp in handles.values():
                try:
                    fp.close()
                except OSError:
                    pass
                if tmp is not None and os.path.exists(tmp):
                    os.remove(tmp)  # uncommitted write: discard
            with self._conns_lock:
                self._live_conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, req: Dict[str, Any], payload: bytes,
                handles: Dict[int, Tuple[Any, Optional[str]]]
                ) -> Tuple[Dict[str, Any], bytes]:
        op = req["op"]
        if op == "open_r":
            fp = open(self._resolve(req["path"]), "rb")
            return {"handle_new": (fp, None)}, b""
        if op == "read":
            fp, _ = handles[req["handle"]]
            return {}, fp.read(req["size"]) if req["size"] >= 0 else fp.read()
        if op == "open_w":
            full = self._resolve(req["path"])
            os.makedirs(os.path.dirname(full), exist_ok=True)
            # server-wide counter: two concurrent write handles on the SAME
            # path (even over one pooled client connection) must not share
            # a temp file
            tmp = full + f".mvfs-tmp-{next(_tmp_ids)}"
            if req.get("append") and os.path.exists(full):
                import shutil
                shutil.copyfile(full, tmp)  # append continues existing bytes
            fp = open(tmp, "ab" if req.get("append") else "wb")
            return {"handle_new": (fp, tmp)}, b""
        if op == "write":
            fp, _ = handles[req["handle"]]
            fp.write(payload)
            return {"written": len(payload)}, b""
        if op == "sync":
            # durability barrier for WAL appends through the scheme; note
            # the temp file only commits (rename) on close, so an open
            # handle's bytes are durable but not yet visible at the final
            # name — see docs/fault_tolerance.md §7 on mvfs-backed WALs
            fp, _ = handles[req["handle"]]
            fp.flush()
            os.fsync(fp.fileno())
            return {}, b""
        if op == "close":
            fp, tmp = handles.pop(req["handle"])
            fp.close()
            if tmp is not None:  # commit: atomic rename over the final name
                os.replace(tmp, tmp[:tmp.index(".mvfs-tmp-")])
            return {}, b""
        if op == "exists":
            return {"exists": os.path.exists(self._resolve(req["path"]))}, b""
        if op == "replace":
            os.replace(self._resolve(req["src"]), self._resolve(req["dst"]))
            return {}, b""
        if op == "makedirs":
            os.makedirs(self._resolve(req["path"]), exist_ok=True)
            return {}, b""
        if op == "listdir":
            full = self._resolve(req["path"])
            names = sorted(os.listdir(full)) if os.path.isdir(full) else []
            return {"names": names}, b""
        if op == "remove":
            os.remove(self._resolve(req["path"]))
            return {}, b""
        raise ValueError(f"mvfs: unknown op {op!r}")


class MvfsRemoteError(IOError):
    """The server processed the request and reported failure (the
    connection itself is healthy)."""


class _MvfsConn:
    """One client connection; serialized request/reply. A transport failure
    evicts this connection from the pool so the next open redials (a
    restarted server must not poison every later filesystem op)."""

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._sock = socket.create_connection((host, port), timeout=30)
        self._lock = threading.Lock()

    def call(self, header: Dict[str, Any], payload: bytes = b""
             ) -> Tuple[Dict[str, Any], bytes]:
        try:
            with self._lock:
                _send(self._sock, header, payload)
                reply, data = _recv(self._sock)
        except OSError:
            _evict(self.host, self.port, self)
            raise
        if "err" in reply:
            raise MvfsRemoteError(f"mvfs server: {reply['err']}")
        return reply, data

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# connection pool: one socket per (host, port) per process — streams and
# filesystem ops share it (requests are serialized per connection)
_conns: Dict[Tuple[str, int], _MvfsConn] = {}
_conns_lock = threading.Lock()


def _conn_for(host: str, port: int) -> _MvfsConn:
    with _conns_lock:
        conn = _conns.get((host, port))
    if conn is not None:
        return conn
    # dial OUTSIDE the global lock: a blackholed endpoint (30s connect
    # timeout) must not stall mvfs traffic to healthy servers
    fresh = _MvfsConn(host, port)
    with _conns_lock:
        conn = _conns.get((host, port))
        if conn is not None:  # raced: keep the first, drop ours
            fresh.close()
            return conn
        _conns[(host, port)] = fresh
    return fresh


def _evict(host: str, port: int, conn: _MvfsConn) -> None:
    """Drop a broken pooled connection so the next open redials."""
    with _conns_lock:
        if _conns.get((host, port)) is conn:
            del _conns[(host, port)]
    conn.close()


def reset_connections() -> None:
    """Drop pooled connections (server restarted / tests)."""
    with _conns_lock:
        for conn in _conns.values():
            conn.close()
        _conns.clear()


def _host_port(uri: URI) -> Tuple[str, int]:
    """host:port from the authority; a missing/garbled port is a malformed
    address (programmer error), reported as such — not a bad stream."""
    host, sep, port = uri.host.rpartition(":")
    if not sep or not port.isdigit():
        log.fatal("mvfs address needs host:port, got %r", uri.raw)
    return host, int(port)


class MvfsStream(Stream):
    """Client-side stream on a served path (``mvfs://host:port/path``)."""

    def __init__(self, uri: URI, mode: str) -> None:
        host, port = _host_port(uri)
        self._conn: Optional[_MvfsConn] = None
        self._writing = "w" in mode or "a" in mode
        op = ("open_w" if self._writing else "open_r")
        try:
            # connect inside the guard: a down server yields a bad stream
            # (good() False), matching the LocalStream/FsspecStream contract
            self._conn = _conn_for(host, port)
            reply, _ = self._conn.call(
                {"op": op, "path": uri.path, "append": "a" in mode})
            self._handle: Optional[int] = reply["handle"]
        except MvfsRemoteError as exc:  # server said no; connection healthy
            log.error("MvfsStream: cannot open %s (%s)", uri.raw, exc)
            self._handle = None
        except OSError as exc:  # transport failure: evict the pooled conn
            log.error("MvfsStream: cannot reach %s (%s)", uri.raw, exc)
            if self._conn is not None:
                _evict(host, port, self._conn)
                self._conn = None
            self._handle = None

    def write(self, data: bytes) -> int:
        if self._handle is None:
            log.fatal("MvfsStream.write on bad stream")
        reply, _ = self._conn.call(
            {"op": "write", "handle": self._handle}, bytes(data))
        return reply["written"]

    def read(self, size: int = -1) -> bytes:
        if self._handle is None:
            log.fatal("MvfsStream.read on bad stream")
        _, data = self._conn.call(
            {"op": "read", "handle": self._handle, "size": size})
        return data

    def good(self) -> bool:
        return self._handle is not None

    def sync(self) -> None:
        if self._handle is not None and self._writing:
            self._conn.call({"op": "sync", "handle": self._handle})

    def close(self) -> None:
        if self._handle is not None:
            self._conn.call({"op": "close", "handle": self._handle})
            self._handle = None


class MvfsFileSystem(FileSystem):
    """Directory operations on a served root — lets CheckpointDriver
    snapshot/restore through the remote scheme."""

    def _split(self, address: str) -> Tuple[_MvfsConn, str]:
        uri = URI.parse(address)
        host, port = _host_port(uri)
        return _conn_for(host, port), uri.path

    def exists(self, address: str) -> bool:
        conn, path = self._split(address)
        reply, _ = conn.call({"op": "exists", "path": path})
        return bool(reply["exists"])

    def replace(self, src: str, dst: str) -> None:
        conn, spath = self._split(src)
        _, dpath = self._split(dst)
        conn.call({"op": "replace", "src": spath, "dst": dpath})

    def makedirs(self, address: str) -> None:
        conn, path = self._split(address)
        conn.call({"op": "makedirs", "path": path})

    def listdir(self, address: str) -> list:
        conn, path = self._split(address)
        reply, _ = conn.call({"op": "listdir", "path": path})
        return reply["names"]

    def remove(self, address: str) -> None:
        conn, path = self._split(address)
        conn.call({"op": "remove", "path": path})


register_scheme("mvfs", lambda uri, mode: MvfsStream(uri, mode))
register_fs("mvfs", MvfsFileSystem())
