"""Top-k retrieval kernels per table kind + the shard-merge algebra.

One ordering contract rules every path in this module AND the shard
router's merge: candidates rank by score DESCENDING, ties by global id
ASCENDING (``np.lexsort((ids, -scores))`` per query row). Because the
single-table engine and the per-shard merge both finish with exactly
this ordering, a global top-k assembled from per-shard partials is
bit-identical — ids and score order — to a single-shard oracle over the
same rows, including at tie boundaries.

Three serving shapes:

* **MatrixServer** — the logical ``[:num_row, :num_col]`` block stays
  device-resident; one jitted fused kernel scores all query rows and
  runs ``jax.lax.top_k`` on device (``lax.top_k`` breaks ties toward
  the lower index, which IS the lower row id — consistent with the
  contract before the host-side reorder even runs).
* **SparseServer** — live rows stack (key-sorted, so index order = id
  order) into one block through the same jitted kernel.
* **TieredSparseServer** — hot rows score as one host block; cold
  segments stream batch-wise through :meth:`TieredStore.scan_blocks`
  under the ``query_scan`` wait-site, scoring **in the compressed
  domain** when the segment is quantized at >= 4 bits:
  ``dot(q, lo + c*step) = lo*sum(q) + step*(q @ c.T)`` (and the row
  norm for cosine from the code moments), decoding otherwise. Scans
  never touch the promotion sketch, the fetch cache, or the hot dict —
  the same no-promotion cold iteration the PR-15 digest path proves —
  so a query leaves the tier hit-rate exactly where it found it.

Host scoring is float32 end-to-end to match the jitted kernels' dtype.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.dashboard import count
from multiverso_tpu.obs.profiler import wait_site
from multiverso_tpu.tables.matrix_table import MatrixServer
from multiverso_tpu.tables.sparse_table import (SparseFTRLServer,
                                                SparseServer,
                                                TieredSparseServer)

_METRICS = ("dot", "cosine")
# zero-norm guard: a zero row/query cosine-scores 0.0 (its dot is 0)
# instead of dividing by zero; shared by the jitted and host paths so
# shard and oracle scores agree bitwise on the raw-row paths
_EPS = np.float32(1e-30)

# compressed-domain floor: below 4 bits the code grid is so coarse that
# scoring it buys nothing over decoding (and 1/2-bit segments are rare
# spill shapes); the ISSUE contract — compressed where bits >= 4
_COMPRESSED_MIN_BITS = 4


def check_request(request) -> Tuple[np.ndarray, int, str]:
    """Validate/normalize one wire query: ``(vecs, k, metric)`` ->
    ``(float32 (n_q, dim) contiguous, k >= 1, metric)``. Raises
    ValueError (-> Reply_Error on the wire) on malformed input."""
    try:
        vecs, k, metric = request
    except (TypeError, ValueError):
        raise ValueError(
            f"query request must be (vecs, k, metric), got {type(request)}")
    vecs = np.ascontiguousarray(vecs, dtype=np.float32)
    if vecs.ndim == 1:
        vecs = vecs.reshape(1, -1)
    if vecs.ndim != 2:
        raise ValueError(f"query vecs must be (n_q, dim), got {vecs.shape}")
    k = int(k)
    if k < 1:
        raise ValueError(f"query k must be >= 1, got {k}")
    metric = str(metric)
    if metric not in _METRICS:
        raise ValueError(f"query metric must be one of {_METRICS}, "
                         f"got {metric!r}")
    return vecs, k, metric


def order_rows(ids: np.ndarray, scores: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Impose THE ordering contract per query row: score descending,
    ties by ascending id. The one piece of algebra the engine and the
    shard merge must share for shard-vs-oracle identity to hold."""
    order = np.lexsort((ids, -scores), axis=-1)
    ids = np.take_along_axis(ids, order, axis=1)
    scores = np.take_along_axis(scores, order, axis=1)
    return (ids.astype(np.int64, copy=False),
            scores.astype(np.float32, copy=False))


def merge_topk(parts: List[Tuple[np.ndarray, np.ndarray]], k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-shard (or per-block) partial top-k replies — possibly
    ragged (a shard owning fewer than k rows replies narrower) — into
    the global top-k under the ordering contract."""
    ids = np.concatenate(
        [np.asarray(p[0], dtype=np.int64).reshape(len(p[0]), -1)
         for p in parts], axis=1)
    scores = np.concatenate(
        [np.asarray(p[1], dtype=np.float32).reshape(len(p[1]), -1)
         for p in parts], axis=1)
    ids, scores = order_rows(ids, scores)
    return ids[:, :k], scores[:, :k]


# -- jitted fused score + top-k (matrix block, sparse block) -----------------

@functools.partial(jax.jit, static_argnames=("k", "cosine"))
def _topk_kernel(block, vecs, k: int, cosine: bool):
    """ONE fused program: score every query row against every table row,
    then ``lax.top_k`` the scored block. Ties break toward the lower
    row index (lax.top_k's contract) — index order is id order at every
    call site, so this agrees with the lexsort contract."""
    q = vecs.astype(jnp.float32)
    b = block.astype(jnp.float32)
    if cosine:
        q = q / jnp.maximum(
            jnp.linalg.norm(q, axis=1, keepdims=True), _EPS)
        b = b / jnp.maximum(
            jnp.linalg.norm(b, axis=1, keepdims=True), _EPS)
    scores = q @ b.T
    return jax.lax.top_k(scores, k)


def _jit_block_topk(block, vecs: np.ndarray, k: int, metric: str
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Run the fused kernel, host-fetch, return (row_indices, scores)
    already in contract order."""
    scores, idx = _topk_kernel(block, vecs, k, metric == "cosine")
    scores = np.asarray(jax.device_get(scores), dtype=np.float32)
    idx = np.asarray(jax.device_get(idx), dtype=np.int64)
    return order_rows(idx, scores)


# -- host scoring (tiered hot block + cold segments) -------------------------

def _score_rows(vecs: np.ndarray, rows: np.ndarray, metric: str
                ) -> np.ndarray:
    """(n_q, n) float32 scores of decoded host rows."""
    if metric == "cosine":
        vecs = vecs / np.maximum(
            np.linalg.norm(vecs, axis=1, keepdims=True), _EPS)
        rows = rows / np.maximum(
            np.linalg.norm(rows, axis=1, keepdims=True), _EPS)
    return (vecs @ rows.T).astype(np.float32, copy=False)


def _score_codes(vecs: np.ndarray, codes: np.ndarray, lo: np.float32,
                 step: np.float32, metric: str) -> np.ndarray:
    """Compressed-domain scores: every row is ``lo + codes*step``
    elementwise, so the dot folds to
    ``lo*sum(q) + step*(q @ codes.T)`` and the row norm (cosine) comes
    from the code moments — no per-element dequantize materializes."""
    lo = np.float32(lo)
    step = np.float32(step)
    if metric == "cosine":
        vecs = vecs / np.maximum(
            np.linalg.norm(vecs, axis=1, keepdims=True), _EPS)
    numer = (lo * vecs.sum(axis=1, keepdims=True)
             + step * (vecs @ codes.T)).astype(np.float32, copy=False)
    if metric == "dot":
        return numer
    width = np.float32(codes.shape[1])
    norm_sq = (width * lo * lo
               + np.float32(2.0) * lo * step * codes.sum(axis=1)
               + step * step * (codes * codes).sum(axis=1))
    norms = np.sqrt(np.maximum(norm_sq, np.float32(0.0)),
                    dtype=np.float32)
    return (numer / np.maximum(norms, _EPS)).astype(np.float32,
                                                    copy=False)


def _block_topk_np(keys: np.ndarray, scores: np.ndarray, k: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-block host top-k in contract order; keys map score columns
    back to global ids."""
    k_eff = min(k, scores.shape[1])
    ids = np.broadcast_to(keys.reshape(1, -1), scores.shape)
    ids, scores = order_rows(np.ascontiguousarray(ids),
                             np.ascontiguousarray(scores))
    return ids[:, :k_eff], scores[:, :k_eff]


# -- per-kind serving --------------------------------------------------------

def _empty(n_q: int) -> Tuple[np.ndarray, np.ndarray]:
    return (np.zeros((n_q, 0), np.int64), np.zeros((n_q, 0), np.float32))


def _query_matrix(table: MatrixServer, vecs: np.ndarray, k: int,
                  metric: str) -> Tuple[np.ndarray, np.ndarray]:
    if vecs.shape[1] != table.num_col:
        raise ValueError(f"query dim {vecs.shape[1]} != table width "
                         f"{table.num_col}")
    if table.num_row == 0:
        return _empty(len(vecs))
    # logical block only: the padded scratch rows must never rank
    block = table.data[:table.num_row, :table.num_col]
    return _jit_block_topk(block, vecs, min(k, table.num_row), metric)


def _query_sparse(table: SparseServer, vecs: np.ndarray, k: int,
                  metric: str) -> Tuple[np.ndarray, np.ndarray]:
    if vecs.shape[1] != table.width:
        raise ValueError(f"query dim {vecs.shape[1]} != table width "
                         f"{table.width}")
    store = table._store
    if not store:
        return _empty(len(vecs))
    keys = np.fromiter(store.keys(), dtype=np.int64, count=len(store))
    keys.sort()  # index order = id order, for the top_k tie contract
    block = np.stack([store[key] for key in keys.tolist()]).astype(
        np.float32, copy=False)
    idx, scores = _jit_block_topk(block, vecs, min(k, len(keys)), metric)
    return keys[idx], scores


def _query_tiered(table: TieredSparseServer, vecs: np.ndarray, k: int,
                  metric: str) -> Tuple[np.ndarray, np.ndarray]:
    if vecs.shape[1] != table.width:
        raise ValueError(f"query dim {vecs.shape[1]} != table width "
                         f"{table.width}")
    parts: List[Tuple[np.ndarray, np.ndarray]] = []
    with wait_site("query_scan"):
        for keys, rows, quant in table._tier.scan_blocks():
            if not len(keys):
                continue
            if quant is not None:
                lo, step, bits, codes = quant
                if bits >= _COMPRESSED_MIN_BITS:
                    count("QUERY_COMPRESSED_SEGMENTS")
                    scores = _score_codes(vecs, codes, lo, step, metric)
                else:
                    # too coarse to fold: dequantize (identical values
                    # to the fetch path's quant_decode) and score plain
                    rows = (np.float32(lo)
                            + codes * np.float32(step)).astype(
                                np.float32, copy=False)
                    scores = _score_rows(vecs, rows, metric)
                count("QUERY_COLD_SEGMENTS_SCANNED")
            else:
                if rows.dtype != np.float32:
                    rows = rows.astype(np.float32)
                scores = _score_rows(vecs, rows, metric)
            parts.append(_block_topk_np(keys, scores, k))
            # running merge: the candidate set stays <= 2k wide however
            # many cold segments the scan streams through
            if len(parts) > 1:
                parts = [merge_topk(parts, k)]
    if not parts:
        return _empty(len(vecs))
    return merge_topk(parts, k)


def query_table(server_table, request) -> Tuple[np.ndarray, np.ndarray]:
    """Serve one query against one server table: ``(vecs, k, metric)``
    -> ``(ids int64 (n_q, k'), scores float32 (n_q, k'))`` with
    ``k' = min(k, rows)``, in contract order. Matrix ids are
    shard-local row indices, sparse/tiered ids are keys — the shard
    router re-globalizes. Refuses kinds without row-shaped scorable
    state loudly."""
    vecs, k, metric = check_request(request)
    table = server_table._unwrapped()
    if isinstance(table, MatrixServer):
        return _query_matrix(table, vecs, k, metric)
    if isinstance(table, TieredSparseServer):
        return _query_tiered(table, vecs, k, metric)
    if isinstance(table, SparseFTRLServer):
        raise TypeError("top-k query is unsupported on FTRL tables: the "
                        "stored (z, n) state is not the weight vector")
    if isinstance(table, SparseServer):
        return _query_sparse(table, vecs, k, metric)
    raise TypeError(f"top-k query needs row-shaped table state; "
                    f"{type(table).__name__} has none")
