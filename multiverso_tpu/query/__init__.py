"""Retrieval query plane: server-side top-k scoring pushdown.

The recommender mile the Get/Add planes never covered: score a block of
query vectors against an embedding table ON the serving process and ship
back only ``(ids, scores)`` — Li et al. (OSDI 2014) ran user-defined
functions on server nodes for exactly this shape of work, and shipping a
10x-over-RAM tiered table to the client to score it there is a
non-starter by construction.

Wire: the slot-free ``Request_Query``/``Reply_Query`` pair
(runtime/message.py) carrying ``(vecs, k, metric)``. Serving: the
:func:`query_table` engine (engine.py) — jitted fused score+top-k for
dense matrix and sparse row blocks, batch-wise cold-segment scans for
tiered tables (compressed-domain scoring where ``tier_cold_bits >= 4``,
never promoting a scanned row). Routing: the shard router merges
per-shard partials with :func:`merge_topk`; replicas serve queries under
the same staleness-budget admission as ``Request_Read``
(docs/serving.md §8).
"""

__all__ = ["merge_topk", "query_table"]


def __getattr__(name):
    # Lazy re-exports (PEP 562): the package root imports THIS package
    # eagerly so its `mv.query(...)` front door can shadow the submodule
    # binding; deferring the engine import keeps that eager bind free of
    # jax/table imports at `import multiverso_tpu` time.
    if name in __all__:
        from multiverso_tpu.query import engine
        return getattr(engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
