"""Per-tenant chargeback over stitched traces (``mv.chargeback``).

The attribution layer (obs/critpath.py) answers *where* fleet time went
— dispatcher, wire, apply, WAL. This module answers the question a
shared parameter-server cluster gets asked first: *which tenant's
traffic bought which fraction of the machine*. Every stitched span
carries the tenant tag its client submit site stamped
(:func:`~multiverso_tpu.runtime.admission.resolve_tenant` over the
``tenant_quota_spec`` flag; untagged traffic folds into ``_default``),
so chargeback is a partition of the same critical-path segments by
tenant: per-tenant share-of-fleet-time (shares sum to 1.0 by
construction), apply+WAL time (the write cost), p99 span latency, plus
the counter-plane columns — bytes pushed, Adds admitted, requests shed
— folded in from the ``TENANT_<t>_*`` families.

Like every diagnostic reader here, it degrades instead of failing:
unreachable endpoints are skipped, and a tenant visible only in
counters (all its spans evicted) still gets a row with zero time.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

from multiverso_tpu.obs.collector import StitchedTrace
from multiverso_tpu.obs.critpath import segments
from multiverso_tpu.obs.trace import DEFAULT_TENANT

# the segment endpoints that count as write cost: time flowing into or
# out of the WAL append and the apply stage (wire-straddling variants
# included — "wire:dispatch_enqueue->apply_add" is still apply pressure)
_APPLY_WAL = ("wal_append", "apply_add")


def _is_apply_wal(segment: str) -> bool:
    name = segment[5:] if segment.startswith("wire:") else segment
    a, _, b = name.partition("->")
    return a in _APPLY_WAL or b in _APPLY_WAL


class ChargebackReport:
    """Per-tenant cost table across many stitched spans.

    ``rows`` is sorted by total attributed time, each row a dict with
    ``tenant``, ``share`` (fraction of all attributed span time —
    summing to ~1.0 whenever any time was attributed), ``total_ms``,
    ``apply_wal_ms``, ``p99_ms``, ``spans`` and the counter-plane
    columns ``bytes`` / ``admitted`` / ``shed``.
    """

    def __init__(self, rows: List[Dict], traces: int,
                 quantile: Optional[float] = None) -> None:
        self.rows = rows
        self.traces = traces
        self.quantile = quantile

    def row(self, tenant: str) -> Optional[Dict]:
        for row in self.rows:
            if row["tenant"] == tenant:
                return row
        return None

    def to_dict(self) -> Dict:
        out = {"traces": self.traces, "rows": self.rows}
        if self.quantile is not None:
            out["quantile"] = self.quantile
        return out

    def render(self) -> str:
        head = "chargeback over %d trace(s)" % self.traces
        if self.quantile is not None:
            head += " (slowest p%g subset)" % (100.0 * self.quantile)
        if not self.rows:
            return head + ": <no tenant-attributable traces>"
        lines = [head,
                 "  %-16s %7s %12s %14s %10s %7s %12s %10s %8s"
                 % ("tenant", "share", "total_ms", "apply+wal_ms",
                    "p99_ms", "spans", "bytes", "admitted", "shed")]
        for row in self.rows:
            lines.append(
                "  %-16s %6.1f%% %12.3f %14.3f %10.3f %7d %12d %10d %8d"
                % (row["tenant"], 100.0 * row["share"], row["total_ms"],
                   row["apply_wal_ms"], row["p99_ms"], row["spans"],
                   row["bytes"], row["admitted"], row["shed"]))
        return "\n".join(lines)

    def display(self) -> str:
        """Print-and-return, the ``Dashboard.display()`` contract."""
        text = self.render()
        print(text, flush=True)
        return text


def charge(traces: Sequence[StitchedTrace],
           counters: Optional[Dict[str, Dict[str, int]]] = None,
           quantile: Optional[float] = None) -> ChargebackReport:
    """Partition span time across tenants.

    ``counters`` is ``{tenant: {"BYTES"|"ADMITTED"|"SHED": total}}`` —
    the counter-plane columns (see :func:`fleet_chargeback` for the
    fleet fold). With ``quantile`` only the slowest ``1 - quantile``
    fraction of spans is charged (tail chargeback), mirroring
    :func:`~multiverso_tpu.obs.critpath.attribute`.
    """
    spans = [t for t in traces if len(t.hops) >= 2]
    if quantile is not None and spans:
        q = min(max(float(quantile), 0.0), 1.0)
        spans = sorted(spans, key=lambda s: s.duration_ns)
        cut = min(len(spans) - 1, int(math.floor(q * len(spans))))
        spans = spans[cut:]
    agg: Dict[str, Dict] = {}

    def row_of(tenant: str) -> Dict:
        return agg.setdefault(tenant, {
            "tenant": tenant, "total_ms": 0.0, "apply_wal_ms": 0.0,
            "spans": 0, "_durations_ms": [],
            "bytes": 0, "admitted": 0, "shed": 0})

    for span in spans:
        row = row_of(span.tenant or DEFAULT_TENANT)
        row["spans"] += 1
        row["_durations_ms"].append(span.duration_ns / 1e6)
        for name, sec in segments(span):
            row["total_ms"] += sec * 1e3
            if _is_apply_wal(name):
                row["apply_wal_ms"] += sec * 1e3
    for tenant, cols in (counters or {}).items():
        row = row_of(tenant)  # counter-only tenants still get a row
        row["bytes"] += int(cols.get("BYTES", 0))
        row["admitted"] += int(cols.get("ADMITTED", 0))
        row["shed"] += int(cols.get("SHED", 0))
    total_ms = sum(row["total_ms"] for row in agg.values())
    rows = sorted(agg.values(), key=lambda r: (-r["total_ms"],
                                               r["tenant"]))
    for row in rows:
        # shares sum to 1.0 by construction: each is this tenant's slice
        # of the SAME total every span contributed to
        row["share"] = (row["total_ms"] / total_ms) if total_ms > 0 else 0.0
        durations = sorted(row.pop("_durations_ms"))
        row["p99_ms"] = (durations[min(len(durations) - 1,
                                       int(0.99 * len(durations)))]
                         if durations else 0.0)
    return ChargebackReport(rows, traces=len(spans), quantile=quantile)


def _tenant_counters(endpoints: Sequence[str],
                     timeout: Optional[float] = None
                     ) -> Dict[str, Dict[str, int]]:
    """Fold the ``TENANT_<t>_<SUFFIX>`` counter families across the
    local dashboard (where the client-side BYTES series lives) and every
    reachable endpoint (where the admission-gate ADMITTED/SHED series
    live) into ``{tenant: {suffix: total}}``."""
    from multiverso_tpu import config
    from multiverso_tpu.dashboard import Dashboard, split_tenant
    from multiverso_tpu.runtime.remote import fetch_stats
    t = float(timeout if timeout is not None
              else config.get_flag("stats_timeout_seconds"))
    merged: Dict[str, int] = dict(Dashboard.snapshot()["counters"])
    local_ep = None
    try:  # an IN-PROCESS server's registry IS the local dashboard —
        # probing it over the wire would double every column
        from multiverso_tpu import Zoo
        local_ep = getattr(Zoo.instance().remote_server, "endpoint", None)
    except Exception:  # noqa: BLE001 — diagnostics degrade, never fail
        local_ep = None
    for ep in endpoints:
        if local_ep is not None and str(ep) == str(local_ep):
            continue
        try:
            snap = fetch_stats(ep, timeout=t)
        except (OSError, RuntimeError):
            continue  # diagnostics degrade, never fail
        for name, value in snap.counters.items():
            merged[name] = merged.get(name, 0) + int(value)
    out: Dict[str, Dict[str, int]] = {}
    for name, value in merged.items():
        tenant, suffix = split_tenant(name)
        if tenant is None:
            continue
        cols = out.setdefault(tenant, {})
        cols[suffix] = cols.get(suffix, 0) + int(value)
    return out


def fleet_chargeback(endpoints: Sequence[str],
                     timeout: Optional[float] = None,
                     quantile: Optional[float] = None) -> ChargebackReport:
    """Collect + stitch + charge across a fleet (``mv.chargeback``):
    tenant-tagged spans from every trace store, counter columns from
    every stats endpoint plus the local dashboard."""
    from multiverso_tpu.obs.collector import collect_traces
    spans = collect_traces(endpoints, timeout=timeout)
    counters = _tenant_counters(endpoints, timeout=timeout)
    return charge(spans, counters=counters, quantile=quantile)
