"""Sampling profiler: the "why is it slow" half of the observability plane.

Two cooperating pieces live here:

* A **wait-site registry** — a per-thread tag (``mark_wait`` /
  ``clear_wait`` / the ``wait_site`` context manager) that blocking code
  paths set around the six canonical places a multiverso thread parks:
  lock acquisition (``fault/lockcheck.py``), socket reads
  (``runtime/net.py:_read_exact``), WAL fsync (``durable/wal.py``),
  dispatcher queue drain (``runtime/server.py``), the shm ring
  backoff ladder (``runtime/shm.py``), and cold-tier segment fetches
  (``store/coldstore.py``).  Marking costs two dict
  operations under the GIL and is paid whether or not a profiler is
  running, so the hooks are always-on and essentially free.

* A **sampling profiler** — :class:`SamplingProfiler` walks
  ``sys._current_frames()`` at ``profile_hz`` from a daemon thread,
  classifies every thread sample as on-CPU or off-CPU (tagged wait site
  first, then a blocking-top-frame heuristic), and accumulates
  per-thread self-time, per-wait-site seconds, and collapsed
  (flamegraph) stacks.  ``sample_once()`` is the deterministic seam —
  tests drive it directly, the sampler thread is just a clock.  In
  continuous mode (``profile_continuous``) each pass feeds ``PROFILE_*``
  gauges into the Dashboard so the ``TimeSeriesRecorder`` picks them up
  like any other metric; ``capture_for_alert`` hands the SLO burn
  engine a profile for every ``slo_burn`` flight dump.

The module deliberately imports nothing from ``runtime/`` and imports
``config``/``dashboard`` lazily, so any module — including the lock
wrappers that are patched in before the package finishes importing —
can depend on the registry without cycles.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

#: Canonical wait-site names, in the order they appear in the docs.
WAIT_SITES = (
    "lock_acquire",       # fault/lockcheck.py  _CheckedLock.acquire
    "net_recv",           # runtime/net.py      _read_exact
    "wal_fsync",          # durable/wal.py      WriteAheadLog.append sync
    "dispatcher_drain",   # runtime/server.py   Server._main pop_all
    "shm_ring_spin",      # runtime/shm.py      Ring read/write backoff
    "tier_cold_fetch",    # store/coldstore.py  ColdStore segment read+decode
)

# thread ident -> wait-site name.  Mutated with single dict ops only
# (atomic under the GIL); read by the sampler without a lock.
_WAIT: Dict[int, str] = {}


def mark_wait(site: str) -> Optional[str]:
    """Tag the calling thread as blocked at ``site``; returns the
    previous tag so nested sites restore correctly via ``clear_wait``."""
    ident = threading.get_ident()
    prev = _WAIT.get(ident)
    _WAIT[ident] = site
    return prev


def clear_wait(prev: Optional[str] = None) -> None:
    """Drop the calling thread's wait tag (or restore the outer one)."""
    ident = threading.get_ident()
    if prev is None:
        _WAIT.pop(ident, None)
    else:
        _WAIT[ident] = prev


def current_wait(ident: Optional[int] = None) -> Optional[str]:
    """The wait-site tag for ``ident`` (default: calling thread)."""
    return _WAIT.get(threading.get_ident() if ident is None else ident)


class wait_site:
    """``with wait_site("net_recv"): sock.recv(...)`` — exception-safe
    mark/clear around a single blocking call."""

    __slots__ = ("site", "_prev")

    def __init__(self, site: str) -> None:
        self.site = site

    def __enter__(self) -> "wait_site":
        self._prev = mark_wait(self.site)
        return self

    def __exit__(self, *exc) -> bool:
        clear_wait(self._prev)
        return False


# Top-frame function names that mean "this thread is parked in the
# runtime, not burning CPU" — the fallback when no wait-site tag is set
# (e.g. a thread blocked in Event.wait or selector poll we don't wrap).
_BLOCKING_FRAMES = frozenset({
    "wait", "_wait_for_tstate_lock", "acquire", "select", "poll",
    "epoll", "accept", "recv", "recv_into", "recvfrom", "read",
    "readinto", "sleep", "get", "join", "sendall", "connect",
})


def _frame_label(frame) -> str:
    stem = os.path.splitext(os.path.basename(frame.f_code.co_filename))[0]
    return "%s.%s" % (stem, frame.f_code.co_name)


class SamplingProfiler:
    """Low-overhead statistical profiler over ``sys._current_frames()``.

    All accumulation happens in :meth:`sample_once`, which tests call
    directly; :meth:`start` merely spawns a daemon thread that calls it
    at ``hz``.  Weights are seconds-per-sample (``1/hz``), so the
    per-thread and per-site totals read as wall-clock attributions.
    """

    def __init__(self, hz: Optional[float] = None,
                 max_frames: Optional[int] = None,
                 emit_metrics: bool = False) -> None:
        if hz is None or max_frames is None:
            from multiverso_tpu import config
            if hz is None:
                hz = config.get_flag("profile_hz")
            if max_frames is None:
                max_frames = config.get_flag("profile_max_frames")
        self.hz = float(hz)
        if self.hz <= 0:
            self.hz = 50.0
        self.max_frames = int(max_frames)
        self.emit_metrics = emit_metrics
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_ns = 0
        self._samples = 0
        self._stacks: Dict[str, int] = {}
        self._threads: Dict[str, Dict] = {}
        self._wait_seconds: Dict[str, float] = {}

    # -- lifecycle ----------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def samples(self) -> int:
        return self._samples

    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        self._stop.clear()
        self._started_ns = time.time_ns()
        self._thread = threading.Thread(
            target=self._run, name="mv-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        thread, self._thread = self._thread, None
        if thread is not None:
            self._stop.set()
            thread.join(timeout=2.0)

    def reset(self) -> None:
        with self._lock:
            self._samples = 0
            self._stacks.clear()
            self._threads.clear()
            self._wait_seconds.clear()

    def _run(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
            except Exception:
                pass  # a torn frame walk must never kill the sampler

    # -- sampling -----------------------------------------------------

    def sample_once(self, weight: Optional[float] = None) -> Dict:
        """Take one sampling pass over every live thread.

        Returns a per-pass summary (``on_cpu``/``off_cpu`` thread counts
        and the wait sites observed) so tests can assert deterministic
        attribution without a sampler thread running.
        """
        w = (1.0 / self.hz) if weight is None else float(weight)
        me = threading.get_ident()
        sampler = self._thread.ident if self._thread is not None else None
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        on_cpu = 0
        off_cpu = 0
        seen_sites: Dict[str, int] = {}
        with self._lock:
            self._samples += 1
            for ident, frame in frames.items():
                if ident == me or ident == sampler:
                    continue
                name = names.get(ident, "tid-%d" % ident)
                site = _WAIT.get(ident)
                if site is None and \
                        frame.f_code.co_name in _BLOCKING_FRAMES:
                    site = "blocked:%s" % frame.f_code.co_name
                info = self._threads.setdefault(
                    name, {"on_cpu": 0.0, "off_cpu": 0.0, "waits": {}})
                if site is None:
                    on_cpu += 1
                    info["on_cpu"] += w
                else:
                    off_cpu += 1
                    info["off_cpu"] += w
                    info["waits"][site] = info["waits"].get(site, 0.0) + w
                    seen_sites[site] = seen_sites.get(site, 0) + 1
                    if not site.startswith("blocked:"):
                        self._wait_seconds[site] = \
                            self._wait_seconds.get(site, 0.0) + w
                stack = self._collapse(name, frame, site)
                self._stacks[stack] = self._stacks.get(stack, 0) + 1
        if self.emit_metrics:
            self._emit(on_cpu, off_cpu)
        return {"on_cpu": on_cpu, "off_cpu": off_cpu, "sites": seen_sites}

    def _collapse(self, thread_name: str, frame, site: Optional[str]) -> str:
        labels: List[str] = []
        f = frame
        while f is not None:
            labels.append(_frame_label(f))
            f = f.f_back
        labels.reverse()  # root first, flamegraph convention
        if len(labels) > self.max_frames:
            labels = labels[-self.max_frames:]
        if site is not None:
            labels.append("[wait:%s]" % site)
        return ";".join([thread_name] + labels)

    def _emit(self, on_cpu: int, off_cpu: int) -> None:
        from multiverso_tpu.dashboard import count, gauge_set
        count("PROFILE_SAMPLES")
        gauge_set("PROFILE_THREADS", on_cpu + off_cpu)
        gauge_set("PROFILE_ON_CPU_THREADS", on_cpu)
        gauge_set("PROFILE_OFF_CPU_THREADS", off_cpu)
        with self._lock:
            waits = dict(self._wait_seconds)
        for site, seconds in waits.items():
            gauge_set(f"PROFILE_WAIT_{site.upper()}_SECONDS", seconds)

    # -- output -------------------------------------------------------

    def collapsed(self, limit: int = 0) -> str:
        """Collapsed-stack (``stack count``) lines, ready for any
        flamegraph renderer; heaviest stacks first."""
        with self._lock:
            items = sorted(self._stacks.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        if limit > 0:
            items = items[:limit]
        return "\n".join("%s %d" % (stack, n) for stack, n in items)

    def wait_seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._wait_seconds)

    def report(self, top_stacks: int = 40) -> Dict:
        """JSON-able snapshot: per-thread self-time, wait-site totals,
        and the heaviest collapsed stacks."""
        with self._lock:
            threads = {
                name: {"on_cpu": info["on_cpu"],
                       "off_cpu": info["off_cpu"],
                       "waits": dict(info["waits"])}
                for name, info in self._threads.items()}
            stacks = sorted(self._stacks.items(),
                            key=lambda kv: (-kv[1], kv[0]))[:top_stacks]
            return {"t_ns": time.time_ns(),
                    "hz": self.hz,
                    "samples": self._samples,
                    "started_ns": self._started_ns,
                    "threads": threads,
                    "wait_seconds": dict(self._wait_seconds),
                    "stacks": [[s, n] for s, n in stacks]}

    def render(self) -> str:
        rep = self.report(top_stacks=10)
        lines = ["profile: %d samples @ %.0f Hz"
                 % (rep["samples"], rep["hz"])]
        for name in sorted(rep["threads"]):
            info = rep["threads"][name]
            total = info["on_cpu"] + info["off_cpu"]
            lines.append("  %-24s %7.3fs self  (%.0f%% off-cpu)"
                         % (name, total,
                            100.0 * info["off_cpu"] / total if total else 0))
            for site, sec in sorted(info["waits"].items(),
                                    key=lambda kv: -kv[1]):
                lines.append("    wait %-20s %7.3fs" % (site, sec))
        return "\n".join(lines)


#: Process-wide profiler, started by ``mv.init`` when
#: ``profile_continuous`` is set; ``mv.profiler()`` hands it out.
PROFILER = SamplingProfiler(hz=50.0, max_frames=24)


def capture_for_alert(profiler: Optional[SamplingProfiler] = None) -> Dict:
    """A profile for a flight dump: the running continuous profiler's
    report if there is one, otherwise a short synchronous burst (~50 ms)
    so even a cold process ships *some* attribution with the alert."""
    p = PROFILER if profiler is None else profiler
    if p.running and p.samples > 0:
        return p.report()
    burst = SamplingProfiler(hz=200.0, max_frames=p.max_frames)
    for _ in range(10):
        burst.sample_once()
        time.sleep(0.005)
    return burst.report()
