"""Per-request tracing + the flight recorder.

The wire ``req_id`` (fault/retry.py's idempotency key) doubles as a span
id: every hop a correlated request takes — client send, frame decode,
server receive, dispatcher enqueue, WAL append, sync-gate defer/release,
apply, reply — appends ``(stage, t_ns)`` to a bounded in-memory trace.
In-process messages carry ``req_id == 0`` and are never traced, so the
hot local path pays nothing but a predicate.

The :class:`FlightRecorder` is the post-mortem half: on an anomalous
event (worker eviction, standby failover, frame CRC reject, a client
failing all pending requests) it appends the last N traces plus a full
dashboard snapshot to a JSONL file (the ``flight_recorder_path`` flag),
so the operator sees exactly which requests were in flight, hop by hop,
when the system misbehaved — without having had tracing "turned on" in
advance. Telemetry must never take down the data path: every dump is
fully guarded.

Stage names are catalogued in ``docs/observability.md``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

MAX_HOPS_PER_TRACE = 64

# The tenant every untagged span (and unclaimed table) folds into — the
# chargeback plane's catch-all bucket. Defined here (the lowest layer
# that stores tags) so admission, collector and chargeback all share one
# constant without import cycles.
DEFAULT_TENANT = "_default"

# Loss counters at the store's bounds, cached Counter objects so the hot
# path stays one dict hit (Dashboard import is deferred: dashboard.py
# imports config which must not cycle back through obs at import time).
_loss_counters: List[Any] = []


def _bound_counters():
    if not _loss_counters:
        from multiverso_tpu.dashboard import Dashboard
        _loss_counters.append(Dashboard.counter("TRACE_EVICTED"))
        _loss_counters.append(Dashboard.counter("TRACE_DROPPED_HOPS"))
    return _loss_counters


class TraceStore:
    """Bounded req_id -> [(stage, t_ns), ...] map. Oldest-trace eviction
    keeps memory constant under sustained traffic; a trace that outgrows
    ``MAX_HOPS_PER_TRACE`` (a retransmit storm) stops growing rather than
    leaking. Both losses are counted (``TRACE_EVICTED`` /
    ``TRACE_DROPPED_HOPS``) so a collector knows its view is partial."""

    def __init__(self, max_traces: int = 512) -> None:
        self.max_traces = int(max_traces)
        self._traces: "OrderedDict[int, List[Tuple[str, int]]]" = \
            OrderedDict()
        # req_id -> tenant tag (only NON-default tags are stored; the
        # map is keyed on live traces, so trace eviction bounds it too)
        self._tenants: Dict[int, str] = {}
        self._lock = threading.Lock()

    def hop(self, req_id: int, stage: str,
            t_ns: Optional[int] = None) -> None:
        if not req_id:
            return
        if t_ns is None:
            t_ns = time.time_ns()
        evicted = dropped = 0
        with self._lock:
            hops = self._traces.get(req_id)
            if hops is None:
                hops = self._traces[req_id] = []
                while len(self._traces) > self.max_traces:
                    old_rid, _ = self._traces.popitem(last=False)
                    self._tenants.pop(old_rid, None)
                    evicted += 1
            if len(hops) < MAX_HOPS_PER_TRACE:
                hops.append((stage, t_ns))
            else:
                dropped = 1
        if evicted or dropped:
            ctr_evicted, ctr_dropped = _bound_counters()
            if evicted:
                ctr_evicted.add(evicted)
            if dropped:
                ctr_dropped.add(dropped)

    def get(self, req_id: int) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._traces.get(req_id, ()))

    def recent(self, n: int) -> List[Tuple[int, List[Tuple[str, int]]]]:
        """The last ``n`` traces in insertion order (oldest first)."""
        with self._lock:
            items = list(self._traces.items())
        return [(rid, list(hops)) for rid, hops in items[-n:]]

    def export(self, n: int) -> Dict[int, List[List[Any]]]:
        """The last ``n`` traces as a JSON/wire-safe dict — the
        ``Control_Traces`` reply payload a TraceCollector stitches."""
        return {rid: [[stage, t_ns] for stage, t_ns in hops]
                for rid, hops in self.recent(n)}

    def tag_tenant(self, req_id: int, tenant: str) -> None:
        """Stamp ``req_id``'s span with its tenant (the submit sites
        call this right after the first hop). Default-tenant tags are
        not stored — absence IS the default — and tags for unknown
        req_ids are dropped, which bounds the map by the trace bound."""
        if not req_id or not tenant or tenant == DEFAULT_TENANT:
            return
        with self._lock:
            if req_id in self._traces:
                self._tenants[req_id] = tenant

    def tenant_of(self, req_id: int) -> str:
        with self._lock:
            return self._tenants.get(req_id, DEFAULT_TENANT)

    def export_tenants(self, n: int) -> Dict[int, str]:
        """Tenant tags for the last ``n`` traces — rides next to
        ``export`` in the ``Control_Traces`` reply (legacy decoders
        ignore the extra key; legacy senders simply omit it)."""
        with self._lock:
            rids = list(self._traces)[-n:]
            return {rid: self._tenants[rid] for rid in rids
                    if rid in self._tenants}

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._tenants.clear()


# Process-global trace store — client and server hops of an in-process
# round trip land in the SAME store (one process), while cross-process
# deployments each record their own half.
TRACES = TraceStore()


def hop(req_id: int, stage: str) -> None:
    """Append one hop to ``req_id``'s trace (no-op for req_id 0)."""
    TRACES.hop(req_id, stage)


def tag_tenant(req_id: int, tenant: str) -> None:
    """Stamp ``req_id``'s span with its resolved tenant (no-op for
    req_id 0 / the default tenant)."""
    TRACES.tag_tenant(req_id, tenant)


class FlightRecorder:
    """Dump-on-anomaly ring: appends an event line, a dashboard snapshot
    line, and the last N trace lines to the ``flight_recorder_path`` JSONL
    file. Configuration is read at dump time (flags may be set after
    import); a missing/empty path disables dumping entirely."""

    def __init__(self, store: TraceStore = TRACES) -> None:
        self.store = store
        self._lock = threading.Lock()
        # reason -> monotonic time of its last written dump (rate limit)
        self._last: Dict[str, float] = {}

    def _suppressed(self, reason: str, path: str) -> Optional[str]:
        """Why this dump must NOT be written (None = write it): the
        per-reason rate limit or the output-file size cap — a flapping
        alert must not fill the disk with identical dumps."""
        from multiverso_tpu import config
        min_interval = float(
            config.get_flag("flight_recorder_min_interval_seconds"))
        if min_interval > 0:
            last = self._last.get(reason)
            now = time.monotonic()
            if last is not None and now - last < min_interval:
                return (f"reason {reason!r} fired {now - last:.2f}s ago "
                        f"(< {min_interval:.2f}s min interval)")
        max_bytes = int(config.get_flag("flight_recorder_max_bytes"))
        if max_bytes > 0:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size >= max_bytes:
                return (f"{path} is {size} bytes "
                        f"(>= flight_recorder_max_bytes={max_bytes})")
        return None

    def dump(self, reason: str, **details: Any) -> Optional[str]:
        """Write one dump; returns the path written, or None when the
        recorder is disabled or the dump is suppressed (size cap /
        per-reason rate limit — counted in FLIGHT_DUMPS_SUPPRESSED).
        Never raises — a failing dump is logged and swallowed (telemetry
        must not take down the data path)."""
        from multiverso_tpu import config, log
        try:
            path = str(config.get_flag("flight_recorder_path"))
            if not path:
                return None
            with self._lock:
                why = self._suppressed(reason, path)
                if why is None:
                    self._last[reason] = time.monotonic()
            if why is not None:
                from multiverso_tpu.dashboard import count
                count("FLIGHT_DUMPS_SUPPRESSED")
                log.info("flight recorder: suppressed %r dump: %s",
                         reason, why)
                return None
            n = max(1, int(config.get_flag("flight_recorder_traces")))
            lines = self._render(reason, n, details)
            with self._lock:
                with open(path, "a", encoding="utf-8") as fp:
                    fp.write(lines)
        except Exception as exc:  # noqa: BLE001 — never propagate
            try:
                log.error("flight recorder: dump for %r failed: %r",
                          reason, exc)
            except Exception:  # noqa: BLE001
                pass
            return None
        from multiverso_tpu.dashboard import count
        count("FLIGHT_DUMPS")
        log.info("flight recorder: dumped %r (+%d trace(s)) -> %s",
                 reason, min(n, len(self.store)), path)
        return path

    def _render(self, reason: str, n: int, details: Dict[str, Any]) -> str:
        from multiverso_tpu.dashboard import Dashboard
        # details go first so a colliding key (e.g. kind=) can never
        # clobber the line-shape discriminator fields
        out = [json.dumps({**{k: _jsonable(v) for k, v in details.items()},
                           "kind": "event", "reason": reason,
                           "t_ns": time.time_ns()})]
        out.append(json.dumps({"kind": "snapshot",
                               **Dashboard.snapshot()}))
        for req_id, hops in self.store.recent(n):
            out.append(json.dumps({
                "kind": "trace", "req_id": req_id,
                "hops": [[stage, t_ns] for stage, t_ns in hops]}))
        return "\n".join(out) + "\n"


def _jsonable(value: Any) -> Any:
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


RECORDER = FlightRecorder()


def flight_dump(reason: str, **details: Any) -> Optional[str]:
    """Trigger a flight-recorder dump (module-level seam the runtime
    calls on eviction / failover / CRC reject / unclean shutdown)."""
    return RECORDER.dump(reason, **details)
