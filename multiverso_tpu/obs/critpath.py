"""Critical-path attribution over stitched traces.

A :class:`~multiverso_tpu.obs.collector.StitchedTrace` is a causally
ordered list of ``(process, stage, t_corrected_ns)`` hops.  The time a
request actually spent is the sum of the gaps between consecutive hops,
so attribution is a segment decomposition:

* ``"stage_a->stage_b"`` — both hops in the same process: time spent
  inside that process between the two stages (dispatch queueing, apply,
  WAL append, ...).
* ``"wire:stage_a->stage_b"`` — the hops straddle a process boundary:
  wire transit plus any remote ingress queueing before the first hop on
  the far side.

:func:`segments` decomposes one span, :func:`dominant` names its single
largest segment, and :func:`attribute` aggregates a whole trace-store
pull into an :class:`AttributionReport` — the "p99 Get: 61% replica
apply-lag wait, 22% wire" table the self-tuning controller (ROADMAP)
needs.  ``mv.attribution(fleet)`` is the front door; ``bench.py
--attribute`` attaches the same table to every bench leg.

Clock-offset correction happens upstream in the collector; this module
only trusts the corrected timestamps (negative gaps from residual skew
clamp to zero rather than producing negative attributions).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from multiverso_tpu.obs.collector import StitchedTrace


def segments(trace: StitchedTrace) -> List[Tuple[str, float]]:
    """Decompose one span into named ``(segment, seconds)`` gaps between
    consecutive hops; residual-skew negative gaps clamp to zero."""
    out: List[Tuple[str, float]] = []
    hops = trace.hops
    for (p0, s0, t0), (p1, s1, t1) in zip(hops, hops[1:]):
        name = ("%s->%s" % (s0, s1) if p0 == p1
                else "wire:%s->%s" % (s0, s1))
        out.append((name, max(0, t1 - t0) / 1e9))
    return out


def dominant(trace: StitchedTrace) -> Optional[Tuple[str, float, float]]:
    """The span's largest segment as ``(name, seconds, share)`` —
    ``share`` is its fraction of the span's total; None for spans with
    fewer than two hops."""
    segs = segments(trace)
    if not segs:
        return None
    total = sum(sec for _, sec in segs)
    name, sec = max(segs, key=lambda kv: kv[1])
    return name, sec, (sec / total if total > 0 else 0.0)


class AttributionReport:
    """Aggregated latency attribution across many stitched spans.

    ``rows`` is sorted by total attributed time, each row a dict with
    ``segment``, ``total_ms``, ``share`` (fraction of all attributed
    time), ``count`` (spans containing the segment), ``mean_ms`` and
    ``max_ms``.  ``profiles`` optionally carries per-process sampling
    profiles pulled over ``Control_Profile``.
    """

    def __init__(self, rows: List[Dict], traces: int,
                 quantile: Optional[float] = None,
                 profiles: Optional[Dict[str, Dict]] = None) -> None:
        self.rows = rows
        self.traces = traces
        self.quantile = quantile
        self.profiles = profiles or {}

    @property
    def dominant(self) -> Optional[Dict]:
        return self.rows[0] if self.rows else None

    def to_dict(self) -> Dict:
        out = {"traces": self.traces, "rows": self.rows}
        if self.quantile is not None:
            out["quantile"] = self.quantile
        if self.profiles:
            out["profiles"] = self.profiles
        return out

    def render(self) -> str:
        head = "attribution over %d trace(s)" % self.traces
        if self.quantile is not None:
            head += " (slowest p%g subset)" % (100.0 * self.quantile)
        if not self.rows:
            return head + ": <no multi-hop traces>"
        lines = [head]
        for row in self.rows:
            lines.append("  %5.1f%%  %9.3f ms  (n=%d, mean %.3f ms)  %s"
                         % (100.0 * row["share"], row["total_ms"],
                            row["count"], row["mean_ms"], row["segment"]))
        for proc in sorted(self.profiles):
            waits = self.profiles[proc].get("wait_seconds") or {}
            if waits:
                top = sorted(waits.items(), key=lambda kv: -kv[1])[:3]
                lines.append("  profile %-24s %s" % (proc, ", ".join(
                    "%s=%.3fs" % (site, sec) for site, sec in top)))
        return "\n".join(lines)


def attribute(traces: Sequence[StitchedTrace],
              quantile: Optional[float] = None,
              profiles: Optional[Dict[str, Dict]] = None
              ) -> AttributionReport:
    """Aggregate segment attributions across ``traces``.

    With ``quantile`` (e.g. ``0.99``) only the slowest ``1 - quantile``
    fraction of spans is aggregated — tail attribution, the Dean et al.
    framing — instead of the whole population.
    """
    spans = [t for t in traces if len(t.hops) >= 2]
    if quantile is not None and spans:
        q = min(max(float(quantile), 0.0), 1.0)
        spans = sorted(spans, key=lambda s: s.duration_ns)
        cut = min(len(spans) - 1, int(math.floor(q * len(spans))))
        spans = spans[cut:]
    agg: Dict[str, Dict] = {}
    for span in spans:
        for name, sec in segments(span):
            row = agg.setdefault(name, {"segment": name, "total_ms": 0.0,
                                        "count": 0, "max_ms": 0.0})
            row["total_ms"] += sec * 1e3
            row["count"] += 1
            row["max_ms"] = max(row["max_ms"], sec * 1e3)
    total_ms = sum(row["total_ms"] for row in agg.values())
    rows = sorted(agg.values(), key=lambda r: (-r["total_ms"],
                                               r["segment"]))
    for row in rows:
        row["share"] = (row["total_ms"] / total_ms) if total_ms > 0 else 0.0
        row["mean_ms"] = row["total_ms"] / row["count"]
    return AttributionReport(rows, traces=len(spans), quantile=quantile,
                             profiles=profiles)


def collect_profiles(endpoints: Sequence[str],
                     timeout: Optional[float] = None) -> Dict[str, Dict]:
    """Pull sampling profiles from a fleet over ``Control_Profile``;
    unreachable endpoints are skipped (diagnostics degrade, never
    fail)."""
    from multiverso_tpu import config
    from multiverso_tpu.runtime.remote import fetch_profile
    t = float(timeout if timeout is not None
              else config.get_flag("stats_timeout_seconds"))
    out: Dict[str, Dict] = {}
    for ep in endpoints:
        try:
            payload = fetch_profile(ep, timeout=t)
        except (OSError, RuntimeError):
            continue
        role = str(payload.get("role", "unknown"))
        out["%s@%s" % (role, ep)] = payload.get("profile") or {}
    return out


def fleet_attribution(endpoints: Sequence[str],
                      timeout: Optional[float] = None,
                      quantile: Optional[float] = None,
                      include_profiles: bool = True) -> AttributionReport:
    """Collect + stitch + attribute across a fleet (``mv.attribution``);
    optionally annotates the report with each process's profile."""
    from multiverso_tpu.obs.collector import collect_traces
    spans = collect_traces(endpoints, timeout=timeout)
    profiles = (collect_profiles(endpoints, timeout=timeout)
                if include_profiles else None)
    return attribute(spans, quantile=quantile, profiles=profiles)
