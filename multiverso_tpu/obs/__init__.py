"""Telemetry subsystem: distributions, tracing, flight recorder, metrics.

What joins the PR-1/2 Counters and the reference-era Monitors
(``dashboard.py``):

* :mod:`~multiverso_tpu.obs.metrics` — log-bucketed :class:`Histogram`
  (p50/p95/p99) and :class:`Gauge`; both live in the Dashboard registry.
* :mod:`~multiverso_tpu.obs.trace` — ``req_id``-keyed per-request hop
  traces and the :class:`FlightRecorder` (dump-on-anomaly JSONL).
* :mod:`~multiverso_tpu.obs.logger` — :class:`MetricsLogger` periodic
  JSONL snapshots (``metrics_path`` / ``metrics_interval_seconds``).
* :mod:`~multiverso_tpu.obs.collector` — :class:`TraceCollector`
  cross-process trace stitching over the ``Control_Traces`` RPC
  (clock-offset estimation + causally-ordered merged spans).
* :mod:`~multiverso_tpu.obs.timeseries` — :class:`TimeSeriesRecorder`
  ring-buffer sampling of the registry (windowed rates / quantiles).
* :mod:`~multiverso_tpu.obs.slo` — declarative SLOs with multi-window
  burn-rate alerting, and the ``mv.top`` fleet view.

Operator treatment: ``docs/observability.md`` (metric catalog, trace
stage list, flight-recorder format, stats RPC usage).
"""

from multiverso_tpu.obs.metrics import (  # noqa: F401
    Gauge, Histogram, StatsSnapshot, log_bounds, merge_stats)
from multiverso_tpu.obs.trace import (  # noqa: F401
    RECORDER, TRACES, FlightRecorder, TraceStore, flight_dump, hop)
from multiverso_tpu.obs.logger import MetricsLogger, load_metrics  # noqa: F401
from multiverso_tpu.obs.collector import (  # noqa: F401
    StitchedTrace, TraceCollector, collect_traces, estimate_offset)
from multiverso_tpu.obs.timeseries import (  # noqa: F401
    TIMESERIES, TimeSeriesRecorder)
from multiverso_tpu.obs.slo import (  # noqa: F401
    Objective, SLOEngine, default_objectives, fleet_top, parse_slo_spec)
