"""Telemetry subsystem: distributions, tracing, flight recorder, metrics.

What joins the PR-1/2 Counters and the reference-era Monitors
(``dashboard.py``):

* :mod:`~multiverso_tpu.obs.metrics` — log-bucketed :class:`Histogram`
  (p50/p95/p99) and :class:`Gauge`; both live in the Dashboard registry.
* :mod:`~multiverso_tpu.obs.trace` — ``req_id``-keyed per-request hop
  traces and the :class:`FlightRecorder` (dump-on-anomaly JSONL).
* :mod:`~multiverso_tpu.obs.logger` — :class:`MetricsLogger` periodic
  JSONL snapshots (``metrics_path`` / ``metrics_interval_seconds``).

Operator treatment: ``docs/observability.md`` (metric catalog, trace
stage list, flight-recorder format, stats RPC usage).
"""

from multiverso_tpu.obs.metrics import (  # noqa: F401
    Gauge, Histogram, StatsSnapshot, log_bounds)
from multiverso_tpu.obs.trace import (  # noqa: F401
    RECORDER, TRACES, FlightRecorder, TraceStore, flight_dump, hop)
from multiverso_tpu.obs.logger import MetricsLogger, load_metrics  # noqa: F401
