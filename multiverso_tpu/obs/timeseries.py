"""Time-series recorder over the dashboard registry.

The dashboard (``multiverso_tpu/dashboard.py``) is cumulative: counters
only grow, histograms only accumulate. That answers "how many, ever" but
not the operator questions — "what is the Get rate NOW", "what was p99
over the last 30 seconds", "is the error rate accelerating". This module
answers them by SAMPLING: a :class:`TimeSeriesRecorder` snapshots every
registered counter, gauge and histogram at a fixed interval into
fixed-size ring buffers, and derives windowed views by differencing:

* :meth:`~TimeSeriesRecorder.rate` — counter delta / elapsed over a
  window (events per second);
* :meth:`~TimeSeriesRecorder.delta` — raw counter delta over a window;
* :meth:`~TimeSeriesRecorder.quantile` — windowed p50/p95/p99 from the
  BUCKET DIFFERENCE of two histogram snapshots (exact on the window's
  own samples — cumulative quantiles would be dominated by history);
* :meth:`~TimeSeriesRecorder.series` — the raw (t, value) points for a
  gauge or counter, for the dashboard's sparklines.

Memory is constant: ``timeseries_samples`` samples deep regardless of
uptime (default 600 x 1 s = a 10-minute window). The sampler thread is
modeled on ``obs/logger.MetricsLogger`` — daemon, interval-driven,
joined on stop; ``sample_now()`` is the deterministic seam tests and the
SLO engine use instead of sleeping.

The SLO burn-rate engine (``obs/slo.py``) is this module's primary
consumer: burn rates are windowed error-budget spends, which are exactly
the windowed rates/quantiles recorded here.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import Dashboard
from multiverso_tpu.obs.metrics import Histogram


class _Sample:
    """One registry snapshot: wall time + flat value maps. Histograms
    keep their full bucket arrays so windows can difference them."""

    __slots__ = ("t", "counters", "gauges", "histograms")

    def __init__(self, t: float, counters: Dict[str, int],
                 gauges: Dict[str, float],
                 histograms: Dict[str, Dict[str, Any]]) -> None:
        self.t = t
        self.counters = counters
        self.gauges = gauges
        self.histograms = histograms


def _hist_diff(name: str, newer: Dict[str, Any],
               older: Optional[Dict[str, Any]]) -> Histogram:
    """The histogram of observations that happened BETWEEN two
    snapshots: bucket-wise subtraction (both snapshots of one cumulative
    histogram share bounds). A reset between samples (counts regressed)
    falls back to the newer snapshot alone."""
    if older is None or older.get("bounds") != newer.get("bounds") \
            or int(older.get("count", 0)) > int(newer.get("count", 0)):
        return Histogram.from_dict(name, newer)
    diff = {
        "bounds": list(newer["bounds"]),
        "buckets": [int(a) - int(b) for a, b in
                    zip(newer["buckets"], older["buckets"])],
        "overflow": int(newer.get("overflow", 0))
        - int(older.get("overflow", 0)),
        "count": int(newer["count"]) - int(older["count"]),
        "sum": float(newer["sum"]) - float(older["sum"]),
        # max is not differencable; the newer cumulative max bounds it
        "max": float(newer.get("max", 0.0)),
    }
    return Histogram.from_dict(name, diff)


class TimeSeriesRecorder:
    """Fixed-memory sampler + windowed query surface (module docstring
    for the model). All queries are lock-consistent reads of the ring;
    an empty or single-sample ring answers conservatively (rate 0,
    quantile from whatever is there)."""

    def __init__(self, interval: Optional[float] = None,
                 samples: Optional[int] = None) -> None:
        # None = flag-driven, re-read at every start (the process-global
        # instance is built at import time, before flags are parsed)
        self._fixed_interval = interval
        self._fixed_samples = samples
        self.interval = float(
            interval if interval is not None
            else config.get_flag("timeseries_interval_seconds"))
        depth = int(samples if samples is not None
                    else config.get_flag("timeseries_samples"))
        self._ring: Deque[_Sample] = deque(maxlen=max(2, depth))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        if self._fixed_interval is None:
            self.interval = float(
                config.get_flag("timeseries_interval_seconds"))
        if self._fixed_samples is None:
            depth = max(2, int(config.get_flag("timeseries_samples")))
            if depth != self._ring.maxlen:
                with self._lock:
                    self._ring = deque(self._ring, maxlen=depth)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mv-timeseries")
        self._thread.start()
        # debug, not info: server child processes hand their first stdout
        # line to harnesses as a readiness marker, and this fires in every
        # mv.init before that marker is printed
        log.debug("timeseries: sampling every %.3gs, %d samples deep",
                  self.interval, self._ring.maxlen)

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sample_now()
            except Exception as exc:  # noqa: BLE001 — telemetry must
                # never die quietly NOR take anything down
                log.error("timeseries: sample failed: %r", exc)

    # -- sampling ------------------------------------------------------------
    def sample_now(self, t: Optional[float] = None) -> _Sample:
        """Take one snapshot immediately (the deterministic seam: tests
        and the SLO engine drive windows without wall-clock sleeps)."""
        snap = Dashboard.snapshot()
        sample = _Sample(
            t=float(t if t is not None else time.time()),
            counters=dict(snap.get("counters", {})),
            gauges=dict(snap.get("gauges", {})),
            histograms=dict(snap.get("histograms", {})))
        with self._lock:
            self._ring.append(sample)
        return sample

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- window anchoring ----------------------------------------------------
    def _window(self, window_seconds: float
                ) -> Tuple[Optional[_Sample], Optional[_Sample]]:
        """(oldest sample inside the window, newest sample). The oldest
        in-window sample anchors the difference; when the ring does not
        reach back that far, the oldest available sample does (the
        window degrades to the recorded history, it never fails)."""
        with self._lock:
            if not self._ring:
                return None, None
            newest = self._ring[-1]
            cutoff = newest.t - float(window_seconds)
            anchor = self._ring[0]
            for sample in self._ring:
                if sample.t >= cutoff:
                    anchor = sample
                    break
        return anchor, newest

    # -- queries -------------------------------------------------------------
    def delta(self, counter: str, window_seconds: float) -> int:
        """Counter increment over the window (0 when unknown)."""
        anchor, newest = self._window(window_seconds)
        if newest is None:
            return 0
        new = int(newest.counters.get(counter, 0))
        old = int(anchor.counters.get(counter, 0)) if anchor else 0
        if anchor is newest:
            # single sample: the whole cumulative value is the best
            # guess for "recent" — better than claiming silence
            return new
        return max(0, new - old)  # reset between samples clamps to 0

    def rate(self, counter: str, window_seconds: float) -> float:
        """Counter events per second over the window."""
        anchor, newest = self._window(window_seconds)
        if newest is None or anchor is None or anchor is newest:
            return 0.0
        dt = newest.t - anchor.t
        if dt <= 0:
            return 0.0
        d = max(0, int(newest.counters.get(counter, 0))
                - int(anchor.counters.get(counter, 0)))
        return d / dt

    def tenant_rates(self, suffix: str,
                     window_seconds: float) -> Dict[str, float]:
        """Per-tenant events/second for one ``TENANT_<t>_<SUFFIX>``
        family (``suffix`` in ``ADMITTED`` / ``SHED`` / ``BYTES``) —
        the windowed view ``mv.top``'s tenant panel and the autopilot's
        per-tenant shed sensor read. Tenants are discovered from the
        newest sample, so a tenant that never emitted is absent (not
        0.0)."""
        from multiverso_tpu.dashboard import split_tenant
        with self._lock:
            newest = self._ring[-1] if self._ring else None
        if newest is None:
            return {}
        out: Dict[str, float] = {}
        for name in newest.counters:
            tenant, suf = split_tenant(name)
            if tenant is not None and suf == suffix.upper():
                out[tenant] = self.rate(name, window_seconds)
        return out

    def gauge(self, name: str) -> float:
        """Latest sampled gauge value."""
        with self._lock:
            if not self._ring:
                return 0.0
            return float(self._ring[-1].gauges.get(name, 0.0))

    def window_histogram(self, name: str,
                         window_seconds: float) -> Optional[Histogram]:
        """The histogram of observations INSIDE the window (bucket
        difference), or None when the histogram was never sampled."""
        anchor, newest = self._window(window_seconds)
        if newest is None:
            return None
        new = newest.histograms.get(name)
        if new is None:
            return None
        old = anchor.histograms.get(name) if (
            anchor is not None and anchor is not newest) else None
        return _hist_diff(name, new, old)

    def quantile(self, name: str, q: float,
                 window_seconds: float) -> float:
        """Windowed quantile of a histogram (0.0 when no samples)."""
        hist = self.window_histogram(name, window_seconds)
        if hist is None or hist.count <= 0:
            return 0.0
        return float(hist.quantile(q))

    def series(self, kind: str, name: str,
               window_seconds: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Raw (t, value) points for sparklines. ``kind`` is
        ``counter`` or ``gauge``."""
        if kind not in ("counter", "gauge"):
            raise ValueError(f"series: unknown kind {kind!r}")
        with self._lock:
            samples = list(self._ring)
        if window_seconds is not None and samples:
            cutoff = samples[-1].t - float(window_seconds)
            samples = [s for s in samples if s.t >= cutoff]
        if kind == "counter":
            return [(s.t, float(s.counters.get(name, 0)))
                    for s in samples]
        return [(s.t, float(s.gauges.get(name, 0.0))) for s in samples]

    def span_seconds(self) -> float:
        """How far back the ring currently reaches."""
        with self._lock:
            if len(self._ring) < 2:
                return 0.0
            return self._ring[-1].t - self._ring[0].t


# Process-global recorder — started by ``mv.init`` (the
# ``timeseries_interval_seconds`` flag), driven manually by tests.
TIMESERIES = TimeSeriesRecorder()
