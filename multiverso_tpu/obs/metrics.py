"""Distribution metrics: log-bucketed histograms and point-in-time gauges.

The reference Multiverso's only observability units were section timers
(``Dashboard::Watch`` count/total/average, ``include/multiverso/
dashboard.h:16-75``) — averages. Li et al. (OSDI'14) and Ho et al.
(NIPS'13) both locate parameter-server performance in TAIL latency and
staleness distributions, which averages cannot see; this module supplies
the missing units. Both types join the :class:`~multiverso_tpu.dashboard.
Dashboard` registry next to Monitor/Counter (``Dashboard.histogram(name)``
/ ``Dashboard.gauge(name)``).

This module is deliberately dependency-free (stdlib only): ``dashboard.py``
imports it lazily, and everything else imports ``dashboard`` — so no import
cycle can form.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Dict, List, Optional


def log_bounds(lowest: float = 1e-6, growth: float = 2.0,
               count: int = 28) -> List[float]:
    """Geometric bucket upper edges ``lowest * growth**i``. The defaults
    cover 1 µs .. ~134 s in factor-of-2 buckets — every latency this
    runtime produces, at a resolution where p99 is meaningful."""
    return [lowest * growth ** i for i in range(count)]


class Histogram:
    """Log-bucketed distribution with quantile estimates.

    Bucket ``i`` covers ``(bounds[i-1], bounds[i]]`` (bucket 0 starts at
    0); one overflow bucket catches values above the last bound. Quantiles
    interpolate linearly within the winning bucket, so on synthetic
    samples the expected value is exactly computable (tested). ``observe``
    is a bisect + two adds under a lock — cheap enough for every request.
    """

    __slots__ = ("name", "bounds", "_counts", "_overflow", "_count", "_sum",
                 "_max", "_lock")

    def __init__(self, name: str, bounds: Optional[List[float]] = None
                 ) -> None:
        self.name = name
        self.bounds = list(bounds) if bounds is not None else log_bounds()
        self._counts = [0] * len(self.bounds)
        self._overflow = 0
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        if value < 0.0 or math.isnan(value):
            value = 0.0
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            if idx < len(self.bounds):
                self._counts[idx] += 1
            else:
                self._overflow += 1
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value

    # -- read side -----------------------------------------------------------
    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        return self._max

    def quantile(self, q: float) -> float:
        """Linear interpolation within the bucket holding rank ``q*count``;
        0.0 on an empty histogram; overflow ranks report the observed max
        (the honest upper bound — the bucket has no finite edge)."""
        with self._lock:
            total = self._count
            if total == 0:
                return 0.0
            rank = q * total
            cum = 0.0
            for i, c in enumerate(self._counts):
                if c and cum + c >= rank:
                    lo = self.bounds[i - 1] if i else 0.0
                    hi = self.bounds[i]
                    frac = (rank - cum) / c
                    return lo + frac * (hi - lo)
                cum += c
            return self._max

    @property
    def p50(self) -> float:
        return self.quantile(0.50)

    @property
    def p95(self) -> float:
        return self.quantile(0.95)

    @property
    def p99(self) -> float:
        return self.quantile(0.99)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self.bounds)
            self._overflow = 0
            self._count = 0
            self._sum = 0.0
            self._max = 0.0

    # -- serialization (stats RPC / metrics JSONL) ---------------------------
    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {"bounds": list(self.bounds),
                    "buckets": list(self._counts),
                    "overflow": self._overflow,
                    "count": self._count,
                    "sum": self._sum,
                    "max": self._max}

    @classmethod
    def from_dict(cls, name: str, data: Dict[str, Any]) -> "Histogram":
        hist = cls(name, bounds=[float(b) for b in data["bounds"]])
        hist._counts = [int(c) for c in data["buckets"]]
        hist._overflow = int(data.get("overflow", 0))
        hist._count = int(data["count"])
        hist._sum = float(data["sum"])
        hist._max = float(data.get("max", 0.0))
        return hist

    def __repr__(self) -> str:
        return (f"Histogram({self.name}: count={self.count}, "
                f"p50={self.p50:.6f}, p95={self.p95:.6f}, "
                f"p99={self.p99:.6f}, max={self.max:.6f})")


class Gauge:
    """Point-in-time numeric value (queue depth, inflight requests, WAL
    backlog bytes, dedup-window occupancy, per-worker staleness): ``set``
    is last-writer-wins, ``add`` is an atomic delta — both under a lock so
    concurrent ``add`` calls never lose increments."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += float(delta)

    @property
    def value(self) -> float:
        return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def __repr__(self) -> str:
        return f"Gauge({self.name}: {self.value:g})"


class StatsSnapshot:
    """A (possibly remote) dashboard snapshot — what ``mv.stats(endpoint)``
    returns. Wraps the serialized dict with typed accessors; histograms are
    rebuilt as real :class:`Histogram` objects so quantile math runs on the
    caller's side with the server's exact bucket counts."""

    def __init__(self, raw: Dict[str, Any]) -> None:
        self.raw = raw
        self.monitors: Dict[str, Dict[str, Any]] = dict(
            raw.get("monitors", {}))
        self.counters: Dict[str, int] = {
            k: int(v) for k, v in raw.get("counters", {}).items()}
        self.gauges: Dict[str, float] = {
            k: float(v) for k, v in raw.get("gauges", {}).items()}
        self._histograms = {
            name: Histogram.from_dict(name, data)
            for name, data in raw.get("histograms", {}).items()}
        # per-member sub-views when this snapshot is a merged shard-group
        # view (mv.stats_all / merge_stats); empty for a single server
        self.shards: List["StatsSnapshot"] = []
        # per-replica sub-views (endpoint -> StatsSnapshot) when the
        # group runs serving read replicas; the replica replay-lag gauges
        # (REPLICA_WATERMARK / REPLICA_LAG_RECORDS) live in these
        self.replicas: Dict[str, "StatsSnapshot"] = {}
        # endpoints that did not answer within the per-endpoint timeout
        # when this is a merged partial view (mv.stats_all): the merge is
        # over the REACHABLE members only, and this says which are not
        self.unreachable: List[str] = []

    def histogram(self, name: str) -> Optional[Histogram]:
        return self._histograms.get(name)

    @property
    def histograms(self) -> Dict[str, Histogram]:
        return dict(self._histograms)

    def counter(self, name: str) -> int:
        return self.counters.get(name, 0)

    def gauge(self, name: str) -> float:
        return self.gauges.get(name, 0.0)

    def __repr__(self) -> str:
        return (f"StatsSnapshot({len(self.monitors)} monitors, "
                f"{len(self.counters)} counters, {len(self.gauges)} gauges, "
                f"{len(self._histograms)} histograms"
                + (f", merged over {len(self.shards)} shards"
                   if self.shards else "") + ")")


def merge_stats(snapshots) -> StatsSnapshot:
    """Fold several members' dashboards into ONE StatsSnapshot — the
    ``mv.stats_all`` merge: counters and gauges sum, monitors sum their
    counts/elapse (average recomputed), histograms merge by BUCKET
    ADDITION so quantiles of the merged view compute on the union of the
    members' exact counts (averaging per-member quantiles would be
    wrong). The members survive as ``.shards`` sub-views."""
    snapshots = list(snapshots)
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    monitors: Dict[str, Dict[str, Any]] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for snap in snapshots:
        for name, value in snap.counters.items():
            counters[name] = counters.get(name, 0) + int(value)
        for name, value in snap.gauges.items():
            gauges[name] = gauges.get(name, 0.0) + float(value)
        for name, mon in snap.monitors.items():
            agg = monitors.setdefault(name, {"count": 0, "elapse_ms": 0.0})
            agg["count"] += int(mon.get("count", 0))
            agg["elapse_ms"] += float(mon.get("elapse_ms", 0.0))
        for name, hist in snap._histograms.items():
            data = hist.to_dict()
            agg = hists.get(name)
            if agg is None:
                hists[name] = {"bounds": list(data["bounds"]),
                               "buckets": list(data["buckets"]),
                               "overflow": data["overflow"],
                               "count": data["count"],
                               "sum": data["sum"],
                               "max": data["max"]}
                continue
            if agg["bounds"] != list(data["bounds"]):
                # differently-bucketed members cannot add bucket-wise;
                # keep the first member's view (sub-views stay exact)
                continue
            agg["buckets"] = [a + b for a, b in zip(agg["buckets"],
                                                    data["buckets"])]
            agg["overflow"] += data["overflow"]
            agg["count"] += data["count"]
            agg["sum"] += data["sum"]
            agg["max"] = max(agg["max"], data["max"])
    for name, agg in monitors.items():
        agg["average_ms"] = (agg["elapse_ms"] / agg["count"]
                             if agg["count"] else 0.0)
    merged = StatsSnapshot({"monitors": monitors, "counters": counters,
                            "gauges": gauges, "histograms": hists})
    merged.shards = snapshots
    return merged
