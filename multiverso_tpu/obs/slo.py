"""SLO burn-rate engine + the live fleet view (``mv.top``).

An SLO here is a declarative objective over one dashboard metric:

* ``histogram`` — a windowed quantile must stay under a latency target
  (Get p99 < 50 ms);
* ``counter`` — a windowed rate must stay under an events-per-second
  target (retries < 1/s);
* ``gauge`` — the sampled value must stay under a level target
  (replica lag < 1000 records, WAL backlog < 64 MiB).

**Burn rate** is how fast the objective's error budget is being spent:
``burn = observed / target``. 1.0 means exactly on budget; 2.0 means
the budget burns twice as fast as it accrues. The engine evaluates each
objective over TWO windows (``windows=SHORT/LONG`` in the spec,
seconds) and fires only when BOTH exceed the burn threshold — the
multi-window rule from the SRE workbook: the short window proves the
problem is happening *now*, the long window proves it is not a blip, so
alerts are both fast and flap-free.

Firing is edge-triggered: on the False→True transition the engine bumps
``SLO_BURN_ALERTS`` and writes a flight-recorder dump tagged
``slo_burn`` (the last N traces + a dashboard snapshot land next to the
alert, so the on-call starts with evidence, not a blank terminal).
Recovery (True→False) is logged but never dumps.

Objectives come from the ``slo_spec`` flag —

    name:histogram=H,p=0.99,target=SEC[,windows=S/L][,burn=B]
    name:counter=C,target=PER_SEC[,...]  name:gauge=G,target=VALUE[,...]

';'-separated — or, when the flag is empty, :func:`default_objectives`
covers the paper system's four canonical SLIs (Get p99, retry rate,
replica lag, WAL backlog).

``mv.top`` (:func:`fleet_top`) is the operator's live view: one
stats+watermark probe per endpoint, rendered as a terminal table (or
HTML with ``format="html"``) of per-process roles, rates, lag and the
local engine's burn status.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import count
from multiverso_tpu.obs.timeseries import TIMESERIES, TimeSeriesRecorder
from multiverso_tpu.obs.trace import flight_dump

_KINDS = ("histogram", "counter", "gauge")


@dataclass
class Objective:
    """One declarative SLO (module docstring for the semantics)."""

    name: str
    kind: str           # histogram | counter | gauge
    metric: str
    target: float
    quantile: float = 0.99            # histogram kind only
    windows: Tuple[float, float] = (60.0, 300.0)  # (short, long) s
    burn_threshold: float = 1.0

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"SLO {self.name!r}: unknown kind "
                             f"{self.kind!r} (want {'|'.join(_KINDS)})")
        if self.target <= 0:
            raise ValueError(f"SLO {self.name!r}: target must be > 0")


@dataclass
class Evaluation:
    """One objective's state at one evaluation instant."""

    objective: Objective
    value_short: float
    value_long: float
    firing: bool
    burn_short: float = field(init=False)
    burn_long: float = field(init=False)

    def __post_init__(self) -> None:
        self.burn_short = self.value_short / self.objective.target
        self.burn_long = self.value_long / self.objective.target


def parse_slo_spec(spec: str) -> List[Objective]:
    """Parse the ``slo_spec`` flag syntax; raises ValueError loudly on a
    malformed clause (a silently-dropped SLO is an unwatched fleet)."""
    objectives: List[Objective] = []
    for clause in str(spec).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        name, sep, body = clause.partition(":")
        if not sep or not name.strip():
            raise ValueError(f"slo_spec clause {clause!r}: want "
                             "'name:kind=METRIC,target=...'")
        kind = metric = None
        kwargs: Dict[str, Any] = {}
        for item in body.split(","):
            key, sep, value = item.strip().partition("=")
            if not sep:
                raise ValueError(f"slo_spec clause {clause!r}: "
                                 f"item {item!r} is not key=value")
            key = key.strip()
            value = value.strip()
            if key in _KINDS:
                kind, metric = key, value
            elif key == "p":
                kwargs["quantile"] = float(value)
            elif key == "target":
                kwargs["target"] = float(value)
            elif key == "burn":
                kwargs["burn_threshold"] = float(value)
            elif key == "windows":
                short, sep, long_ = value.partition("/")
                kwargs["windows"] = (float(short),
                                     float(long_) if sep else
                                     float(short) * 5.0)
            else:
                raise ValueError(f"slo_spec clause {clause!r}: "
                                 f"unknown key {key!r}")
        if kind is None or "target" not in kwargs:
            raise ValueError(f"slo_spec clause {clause!r}: needs a "
                             "kind=METRIC item and a target")
        objectives.append(Objective(name=name.strip(), kind=kind,
                                    metric=metric, **kwargs))
    return objectives


def default_objectives() -> List[Objective]:
    """The four canonical SLIs of this system, with lenient targets —
    operators tighten via ``slo_spec``; these exist so a bare fleet is
    never unwatched."""
    return [
        Objective(name="get_p99", kind="histogram",
                  metric="CLIENT_REQUEST_SECONDS", quantile=0.99,
                  target=0.250),
        Objective(name="retry_rate", kind="counter",
                  metric="CLIENT_RETRIES", target=5.0),
        Objective(name="replica_lag", kind="gauge",
                  metric="REPLICA_LAG_RECORDS", target=10_000.0),
        Objective(name="wal_backlog", kind="gauge",
                  metric="WAL_BACKLOG_BYTES", target=256 * 1024 * 1024),
    ]


class SLOEngine:
    """Evaluates objectives against a :class:`TimeSeriesRecorder` on a
    timer (``slo_check_interval_seconds``); ``evaluate_now()`` is the
    deterministic seam chaos tests drive directly."""

    def __init__(self, recorder: TimeSeriesRecorder = TIMESERIES,
                 objectives: Optional[Sequence[Objective]] = None) -> None:
        self.recorder = recorder
        if objectives is None:
            spec = str(config.get_flag("slo_spec"))
            objectives = (parse_slo_spec(spec) if spec.strip()
                          else default_objectives())
        self.objectives: List[Objective] = list(objectives)
        self._firing: Dict[str, bool] = {}
        self._firing_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last: List[Evaluation] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mv-slo")
        self._thread.start()
        # debug, not info: fires inside every mv.init, which must not write
        # to stdout before a server child's "serving ..." readiness marker
        log.debug("slo: watching %d objective(s): %s",
                  len(self.objectives),
                  ", ".join(o.name for o in self.objectives))

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)

    def _run(self) -> None:
        interval = float(config.get_flag("slo_check_interval_seconds"))
        while not self._stop.wait(max(0.05, interval)):
            try:
                self.evaluate_now()
            except Exception as exc:  # noqa: BLE001 — the watcher must
                # outlive any single bad evaluation
                log.error("slo: evaluation failed: %r", exc)

    # -- evaluation ----------------------------------------------------------
    def _value(self, obj: Objective, window: float) -> float:
        if obj.kind == "histogram":
            return self.recorder.quantile(obj.metric, obj.quantile,
                                          window)
        if obj.kind == "counter":
            return self.recorder.rate(obj.metric, window)
        return self.recorder.gauge(obj.metric)

    def evaluate_now(self) -> List[Evaluation]:
        """Evaluate every objective against the recorder's CURRENT
        rings (callers sample first — the engine never sleeps here)."""
        evals: List[Evaluation] = []
        for obj in self.objectives:
            short_w, long_w = obj.windows
            v_short = self._value(obj, short_w)
            v_long = self._value(obj, long_w)
            firing = (v_short > obj.target * obj.burn_threshold
                      and v_long > obj.target * obj.burn_threshold)
            ev = Evaluation(objective=obj, value_short=v_short,
                            value_long=v_long, firing=firing)
            was = self._firing.get(obj.name, False)
            self._firing[obj.name] = firing
            if firing and not was:
                self._firing_since[obj.name] = time.time()
                count("SLO_BURN_ALERTS")
                log.error("slo: %s BURNING — short=%.6g long=%.6g "
                          "target=%.6g (burn %.2fx/%.2fx)", obj.name,
                          v_short, v_long, obj.target, ev.burn_short,
                          ev.burn_long)
                # objective_kind, not kind= — the recorder's own "kind"
                # field discriminates event/snapshot/trace lines
                details = dict(slo=obj.name,
                               objective_kind=obj.kind,
                               metric=obj.metric, target=obj.target,
                               value_short=v_short, value_long=v_long,
                               burn_short=ev.burn_short,
                               burn_long=ev.burn_long)
                if bool(config.get_flag("profile_on_alert")):
                    # every slo_burn dump ships a "why": the continuous
                    # profiler's report, or a short burst on cold
                    # processes (capture failure must not eat the alert)
                    try:
                        from multiverso_tpu.obs.profiler import \
                            capture_for_alert
                        details["profile"] = capture_for_alert()
                    except Exception:  # noqa: BLE001
                        pass
                flight_dump("slo_burn", **details)
            elif was and not firing:
                self._firing_since.pop(obj.name, None)
                log.info("slo: %s recovered (short=%.6g target=%.6g)",
                         obj.name, v_short, obj.target)
            evals.append(ev)
        self.last = evals
        return evals

    def firing(self) -> List[str]:
        return [name for name, on in self._firing.items() if on]

    def is_firing(self, name: str) -> bool:
        """Is objective ``name`` currently burning? (queryable state the
        autopilot's sensors read instead of parsing dumps)"""
        return bool(self._firing.get(name, False))

    def status(self) -> Dict[str, Any]:
        """The engine's queryable state: per-objective last evaluation
        plus firing/since — the machine-readable twin of render()."""
        objectives = []
        for ev in self.last:
            o = ev.objective
            objectives.append({
                "name": o.name, "kind": o.kind, "metric": o.metric,
                "target": o.target, "value_short": ev.value_short,
                "value_long": ev.value_long, "burn_short": ev.burn_short,
                "burn_long": ev.burn_long, "firing": ev.firing,
                "firing_since": self._firing_since.get(o.name)})
        return {"firing": self.firing(), "objectives": objectives}

    def render(self) -> str:
        """One line per objective — the ``mv.top`` SLO panel."""
        if not self.last:
            return "(no SLO evaluations yet)"
        lines = [f"{'slo':<16} {'kind':<10} {'short':>12} {'long':>12} "
                 f"{'target':>12} {'burn':>7} {'state':<8}"]
        for ev in self.last:
            o = ev.objective
            state = "BURNING" if ev.firing else "ok"
            lines.append(f"{o.name:<16} {o.kind:<10} "
                         f"{ev.value_short:>12.6g} {ev.value_long:>12.6g} "
                         f"{o.target:>12.6g} {ev.burn_short:>6.2f}x "
                         f"{state:<8}")
        return "\n".join(lines)


# -- the live fleet view (mv.top) ---------------------------------------------

def _probe_fleet(endpoints: Sequence[str],
                 timeout: float) -> List[Dict[str, Any]]:
    """One stats + one watermark probe per endpoint, concurrently;
    unreachable endpoints report as such instead of failing the view."""
    from multiverso_tpu.runtime.remote import fetch_stats, fetch_watermark
    rows: List[Optional[Dict[str, Any]]] = [None] * len(endpoints)

    def probe(i: int, ep: str) -> None:
        row: Dict[str, Any] = {"endpoint": ep}
        try:
            wm = fetch_watermark(ep, timeout=timeout)
            row.update(role=str(wm.get("role", "?")),
                       watermark=int(wm.get("watermark", -1)),
                       lag=int(wm.get("lag", 0) or 0))
        except (OSError, RuntimeError):
            row.update(role="unreachable", watermark=-1, lag=-1)
            rows[i] = row
            return
        try:
            stats = fetch_stats(ep, timeout=timeout)
            gets = (stats.counter("READS_SERVED_PRIMARY")
                    + stats.counter("READS_SERVED_REPLICA"))
            get_hist = stats.histogram("SERVER_PROCESS_GET_MSG")
            add_hist = stats.histogram("SERVER_PROCESS_ADD_MSG")
            row.update(
                gets=gets,
                adds=add_hist.count if add_hist is not None else 0,
                get_p99_ms=(get_hist.p99 * 1e3
                            if get_hist is not None else 0.0),
                dumps=stats.counter("FLIGHT_DUMPS"),
                alerts=stats.counter("SLO_BURN_ALERTS"))
        except (OSError, RuntimeError):
            pass  # watermark answered; render the partial row
        rows[i] = row

    threads = [threading.Thread(target=probe, args=(i, ep), daemon=True,
                                name="mv-top-probe")
               for i, ep in enumerate(endpoints)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 1.0)
    return [r if r is not None
            else {"endpoint": endpoints[i], "role": "unreachable",
                  "watermark": -1, "lag": -1}
            for i, r in enumerate(rows)]


def fleet_top(endpoints: Sequence[str],
              engine: Optional[SLOEngine] = None,
              timeout: Optional[float] = None,
              format: str = "text") -> str:
    """Render the live fleet view (``mv.top``): one row per serving
    endpoint (role, watermark, lag, served Gets/Adds, server-side Get
    p99, flight dumps, burn alerts) plus the local SLO panel.
    ``format`` is ``text`` (terminal) or ``html`` (a self-contained
    page for a browser tab an operator leaves open)."""
    t = float(timeout if timeout is not None
              else config.get_flag("stats_timeout_seconds"))
    rows = _probe_fleet(list(endpoints), t)
    if format == "html":
        return _render_html(rows, engine)
    if format != "text":
        raise ValueError(f"fleet_top: unknown format {format!r} "
                         "(want 'text' or 'html')")
    cols = (f"{'endpoint':<24} {'role':<12} {'wmark':>8} {'lag':>6} "
            f"{'gets':>9} {'adds':>9} {'p99_ms':>9} {'dumps':>6} "
            f"{'alerts':>7}")
    lines = [f"== mv.top @ {time.strftime('%H:%M:%S')} "
             f"({len(rows)} endpoint(s)) ==", cols]
    for r in rows:
        lines.append(
            f"{r['endpoint']:<24} {r.get('role', '?'):<12} "
            f"{r.get('watermark', -1):>8} {r.get('lag', -1):>6} "
            f"{r.get('gets', 0):>9} {r.get('adds', 0):>9} "
            f"{r.get('get_p99_ms', 0.0):>9.3f} {r.get('dumps', 0):>6} "
            f"{r.get('alerts', 0):>7}")
    lines.append("")
    lines.append(engine.render() if engine is not None
                 else "(no SLO engine attached — pass engine=)")
    tenant_panel = _render_tenants()
    if tenant_panel:
        lines.append("")
        lines.append(tenant_panel)
    return "\n".join(lines)


def _render_tenants(window: float = 30.0) -> str:
    """The per-tenant rate panel (chargeback plane): windowed admit and
    shed rates out of the local ``TENANT_<t>_*`` series. Empty string
    when no tenant traffic was ever recorded — single-tenant fleets keep
    today's mv.top byte-for-byte."""
    from multiverso_tpu.obs.timeseries import TIMESERIES
    admitted = TIMESERIES.tenant_rates("ADMITTED", window)
    shed = TIMESERIES.tenant_rates("SHED", window)
    tenants = sorted(set(admitted) | set(shed))
    if not tenants:
        return ""
    lines = [f"{'tenant':<16} {'admit/s':>9} {'shed/s':>9}"]
    for tenant in tenants:
        lines.append(f"{tenant:<16} {admitted.get(tenant, 0.0):>9.2f} "
                     f"{shed.get(tenant, 0.0):>9.2f}")
    return "\n".join(lines)


def _render_html(rows: List[Dict[str, Any]],
                 engine: Optional[SLOEngine]) -> str:
    def esc(s: Any) -> str:
        return (str(s).replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;"))

    body = ["<html><head><title>mv.top</title><style>",
            "body{font-family:monospace;background:#111;color:#ddd}",
            "table{border-collapse:collapse}",
            "td,th{border:1px solid #444;padding:4px 10px}",
            ".burn{color:#f55;font-weight:bold}.ok{color:#5f5}",
            "</style></head><body>",
            f"<h2>mv.top &mdash; {esc(time.strftime('%H:%M:%S'))}</h2>",
            "<table><tr><th>endpoint</th><th>role</th><th>watermark</th>"
            "<th>lag</th><th>gets</th><th>adds</th><th>get p99 (ms)</th>"
            "<th>dumps</th><th>alerts</th></tr>"]
    for r in rows:
        body.append(
            "<tr>" + "".join(
                f"<td>{esc(r.get(k, ''))}</td>"
                for k in ("endpoint", "role", "watermark", "lag", "gets",
                          "adds", "get_p99_ms", "dumps", "alerts"))
            + "</tr>")
    body.append("</table>")
    if engine is not None and engine.last:
        body.append("<h3>SLOs</h3><table><tr><th>slo</th><th>short</th>"
                    "<th>long</th><th>target</th><th>burn</th>"
                    "<th>state</th></tr>")
        for ev in engine.last:
            cls = "burn" if ev.firing else "ok"
            state = "BURNING" if ev.firing else "ok"
            body.append(
                f"<tr><td>{esc(ev.objective.name)}</td>"
                f"<td>{ev.value_short:.6g}</td>"
                f"<td>{ev.value_long:.6g}</td>"
                f"<td>{ev.objective.target:.6g}</td>"
                f"<td>{ev.burn_short:.2f}x</td>"
                f'<td class="{cls}">{state}</td></tr>')
        body.append("</table>")
    body.append("</body></html>")
    return "\n".join(body)
