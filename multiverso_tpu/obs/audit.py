"""Fleet integrity auditing: state digests + the continuous auditor.

The observability stack can say where time goes (trace stitching,
profiler/critpath) but not whether the fleet's STATE is correct: replicas
and standbys replay their primary's WAL and are never compared against
it, so a bad apply — bit rot, a corrupted row the wire CRC could not see
because it happened after decode, a replay bug — survives silently until
a failover serves it. This module closes that gap:

* :func:`table_digest` — an order-independent content digest over a
  table's ``(id, row-bytes)`` pairs. Order independence (a commutative
  XOR + sum fold over per-row hashes) is load-bearing twice: dict
  iteration order differs across processes, and tiered tables stream
  ``hot then cold`` while their plain twins stream insertion order. The
  fold is streamed row-at-a-time, so a tiered table digests its cold
  segments WITHOUT promoting them (``TieredStore.items`` decodes
  segment-at-a-time and never admits — the working set survives an
  audit).
* ``Control_Digest`` repliers (runtime/remote.py primaries,
  durable/standby.py replicas) call :func:`digest_payload` under their
  dispatcher seam, so the ``(digest, watermark)`` pair is exact for the
  state observed.
* :class:`FleetAuditor` (``mv.audit``) — pulls digests from every
  primary and replica, compares them at a common watermark, verifies an
  acked-Add conservation ledger (a member's watermark must never regress
  within one layout version — a regression means acknowledged records
  vanished), and on mismatch fires ``AUDIT_DIVERGENCE`` through the
  flight-recorder path with both digests and the watermark vector
  attached (docs/observability.md, docs/fault_tolerance.md).
"""

from __future__ import annotations

import hashlib
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import Dashboard, count
from multiverso_tpu.obs.trace import flight_dump

_FOLD_MOD = 1 << 128


def _row_hash(key: int, row_bytes: bytes) -> int:
    h = hashlib.blake2b(struct.pack("<q", int(key)) + row_bytes,
                        digest_size=16).digest()
    return int.from_bytes(h, "little")


def _iter_rows(server) -> Any:
    """Yield ``(id, row-bytes)`` for a server table, streamed.

    Row bytes are the canonical dtype encoding — exactly what the table's
    ``store()`` writes per row — so digests interchange across backends
    of one kind: a tiered table (whose cold rows decode through the
    quantized segment codec) digests equal to a plain table loaded from
    its snapshot, because the snapshot carried those same decoded bytes.

    Kinds without a row map (dense array/matrix) fold their canonical
    ``store()`` stream as one pseudo-row under id -1: still comparable
    across processes, just not incremental.
    """
    z = getattr(server, "_z", None)
    n = getattr(server, "_n", None)
    if isinstance(z, dict) and isinstance(n, dict):
        # FTRL: the (z, n) accumulator pair IS the row state
        for k, zv in z.items():
            yield int(k), zv.tobytes() + n[k].tobytes()
        return
    tier = getattr(server, "_tier", None)
    if tier is not None:
        dtype = getattr(server, "dtype", None) or server.value_dtype
        for k, row in tier.items():
            yield int(k), np.ascontiguousarray(row, dtype=dtype).tobytes()
        return
    store = getattr(server, "_store", None)
    if isinstance(store, dict):
        dtype = getattr(server, "dtype", None) or getattr(
            server, "value_dtype", None)
        for k, v in store.items():
            if isinstance(v, np.ndarray) and dtype is not None:
                yield int(k), np.ascontiguousarray(v,
                                                   dtype=dtype).tobytes()
            elif dtype is not None and isinstance(v, (int, float, complex,
                                                      np.generic)):
                yield int(k), np.asarray(v, dtype=dtype).tobytes()
            else:
                # host KV stores arbitrary python objects; their repr is
                # the only stable byte encoding available
                yield int(k), repr(v).encode("utf-8")
        return
    from multiverso_tpu.tables.kv_table import DeviceKVServer
    if isinstance(server, DeviceKVServer):
        dtype = server.value_dtype
        for k, v in server.process_get((None, None)).items():
            yield int(k), np.asarray(v, dtype=dtype).tobytes()
        return
    from multiverso_tpu import io as mv_io
    stream = mv_io.MemoryStream()
    server.store(stream)
    yield -1, stream.getvalue()


def table_digest(server) -> Dict[str, Any]:
    """Order-independent content digest of one server table:
    ``{"digest": <32 hex chars>, "rows": <count>}``. Accepts a worker
    handle or a server table."""
    server = getattr(server, "_server_table", server)
    acc_xor = 0
    acc_sum = 0
    rows = 0
    for key, row_bytes in _iter_rows(server):
        h = _row_hash(key, row_bytes)
        acc_xor ^= h
        acc_sum = (acc_sum + h) % _FOLD_MOD
        rows += 1
    final = hashlib.blake2b(
        acc_xor.to_bytes(16, "little") + acc_sum.to_bytes(16, "little")
        + struct.pack("<q", rows), digest_size=16)
    return {"digest": final.hexdigest(), "rows": rows}


def digest_payload(tables: Dict[int, Any], role: str, endpoint: str,
                   watermark: int, layout_version: int) -> Dict[str, Any]:
    """The ``Control_Reply_Digest`` payload: per-table digests plus the
    identity needed to compare them — MUST be computed under the serving
    process's dispatcher seam so ``watermark`` is exact for the state
    digested."""
    return {"role": role, "endpoint": endpoint,
            "watermark": int(watermark),
            "layout_version": int(layout_version),
            "tables": {int(tid): table_digest(table)
                       for tid, table in sorted(tables.items())}}


def _digest_tables(payload: Dict[str, Any]) -> Dict[int, Dict[str, Any]]:
    # wire codecs may stringify int keys; normalize for comparison
    return {int(tid): d for tid, d in (payload.get("tables") or {}).items()}


class FleetAuditor:
    """Continuous primary↔replica↔standby digest comparison (``mv.audit``).

    ``fleet`` is anything :func:`mv._fleet_endpoints` understands — a
    ShardGroup, a layout manifest, endpoint lists — but shard structure
    matters here: digests are compared per shard, each primary against
    its own replica fleet. A cut manifest (``mv.cut_fleet``) may ride
    along as ``manifest``; divergence flight dumps carry it so the
    operator holding the dump also holds the restore point.

    Each :meth:`check`:

    * pulls ``Control_Digest`` from the shard's primary and every
      replica (``AUDIT_UNREACHABLE`` per member that does not answer);
    * compares digests only between members at the SAME watermark — a
      replica mid-catch-up is lagging, not diverged
      (``AUDIT_SKEW_SKIPS``);
    * verifies the conservation ledger: within one layout version a
      member's watermark must never regress (acked Adds are records;
      records vanishing is loss). Migration fences bump the layout
      version, which resets the expectation — a post-cutover member
      legitimately restarts its WAL lineage;
    * on any mismatch counts ``AUDIT_DIVERGENCE`` and (edge-triggered,
      like the SLO burn alerts) fires one ``audit_divergence`` flight
      dump with both digests and the watermark vector.
    """

    def __init__(self, fleet: Any,
                 interval: Optional[float] = None,
                 timeout: Optional[float] = None,
                 manifest: Optional[Dict[str, Any]] = None,
                 probe: Optional[Callable[..., Dict[str, Any]]] = None
                 ) -> None:
        self.primaries: List[str] = [
            str(e) for e in getattr(fleet, "endpoints",
                                    fleet if isinstance(fleet, (list, tuple))
                                    else [fleet])]
        if isinstance(fleet, dict):
            self.primaries = [str(e) for e in fleet.get("endpoints", [])]
            self.replicas = [list(r) for r in fleet.get("replicas", [])]
        else:
            self.replicas = [
                [str(e) for e in fleet_eps] for fleet_eps in
                (getattr(fleet, "replica_endpoints", None) or [])]
        self.interval = float(
            interval if interval is not None
            else config.get_flag("audit_interval_seconds"))
        self.timeout = float(
            timeout if timeout is not None
            else config.get_flag("audit_timeout_seconds"))
        self.manifest = manifest
        if probe is None:
            from multiverso_tpu.runtime.remote import fetch_digest
            probe = fetch_digest
        self._probe = probe
        self.last_report: Optional[Dict[str, Any]] = None
        self._divergent = False  # edge-trigger state for the flight dump
        self._divergent_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one sweep -----------------------------------------------------------
    def check(self) -> Dict[str, Any]:
        count("AUDIT_RUNS")
        divergences: List[Dict[str, Any]] = []
        unreachable: List[str] = []
        skews = 0
        shards: List[Dict[str, Any]] = []
        for k, primary_ep in enumerate(self.primaries):
            members = [("primary", primary_ep)]
            if k < len(self.replicas):
                members += [("replica", ep) for ep in self.replicas[k]]
            payloads: Dict[str, Dict[str, Any]] = {}
            for role, ep in members:
                try:
                    payloads[ep] = self._probe(ep, timeout=self.timeout)
                except (OSError, RuntimeError):
                    count("AUDIT_UNREACHABLE")
                    unreachable.append(ep)
            divergences.extend(self._ledger_check(payloads))
            primary = payloads.get(primary_ep)
            watermarks = {ep: int(p.get("watermark", -1))
                          for ep, p in payloads.items()}
            if primary is not None:
                p_wm = int(primary.get("watermark", -1))
                p_tables = _digest_tables(primary)
                for _role, ep in members[1:]:
                    replica = payloads.get(ep)
                    if replica is None:
                        continue
                    if int(replica.get("watermark", -1)) != p_wm:
                        # lag is the watermark probe's business; digests
                        # of different prefixes are incomparable
                        count("AUDIT_SKEW_SKIPS")
                        skews += 1
                        continue
                    for tid, want in p_tables.items():
                        got = _digest_tables(replica).get(tid)
                        if got is None or got["digest"] != want["digest"]:
                            divergences.append({
                                "kind": "digest_mismatch", "shard": k,
                                "table_id": tid, "watermark": p_wm,
                                "primary": {"endpoint": primary_ep, **want},
                                "replica": {"endpoint": ep,
                                            **(got or {"digest": None,
                                                       "rows": -1})}})
            shards.append({"shard": k, "watermarks": watermarks})
        report = {"divergences": divergences, "unreachable": unreachable,
                  "skews": skews, "shards": shards,
                  "ok": not divergences}
        self.last_report = report
        if divergences:
            count("AUDIT_DIVERGENCE", len(divergences))
            if not self._divergent:
                # edge-triggered like the SLO burn alerts: one dump per
                # transition into divergence, not one per sweep — the
                # condition persists until repaired and the recorder
                # must not fill with copies of the same fact
                flight_dump("audit_divergence",
                            divergences=divergences,
                            watermarks=[s["watermarks"] for s in shards],
                            manifest=self.manifest)
            if not self._divergent:
                self._divergent_since = time.time()
            self._divergent = True
            log.error("audit: %d divergence(s) across the fleet: %r",
                      len(divergences), divergences[:3])
        else:
            self._divergent = False
            self._divergent_since = None
        return report

    @property
    def divergent(self) -> bool:
        """Is the fleet currently diverged (as of the last sweep)? The
        queryable twin of the ``audit_divergence`` dump — the autopilot's
        safety interlock polls this instead of parsing the recorder."""
        return self._divergent

    def status(self) -> Dict[str, Any]:
        """Machine-readable auditor state: divergence flag + since-time,
        plus the last sweep's summary counts."""
        report = self.last_report or {}
        return {"divergent": self._divergent,
                "divergent_since": self._divergent_since,
                "divergences": len(report.get("divergences", [])),
                "unreachable": list(report.get("unreachable", [])),
                "skews": int(report.get("skews", 0)),
                "checked": self.last_report is not None}

    def _ledger_check(self, payloads: Dict[str, Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
        """Acked-Add conservation: a member's watermark regressing within
        one layout version means records it acknowledged (or applied)
        no longer exist. A layout-version bump — a migration fence —
        resets the expectation: post-cutover members legitimately start
        a fresh WAL lineage."""
        out: List[Dict[str, Any]] = []
        ledger = getattr(self, "_ledger", None)
        if ledger is None:
            ledger = self._ledger = {}
        for ep, payload in payloads.items():
            lv = int(payload.get("layout_version", -1))
            wm = int(payload.get("watermark", -1))
            prev = ledger.get(ep)
            if prev is not None and prev[0] == lv and wm < prev[1]:
                out.append({"kind": "watermark_regression", "endpoint": ep,
                            "layout_version": lv, "watermark": wm,
                            "previous": prev[1]})
            ledger[ep] = (lv, wm)
        return out

    # -- background mode -----------------------------------------------------
    def start(self) -> "FleetAuditor":
        if self.interval <= 0:
            log.fatal("FleetAuditor.start needs audit_interval_seconds > 0 "
                      "(or interval=); use check() for one-shot audits")
        if self._thread is not None:
            return self
        # a dedicated auditor process (operator box, cron job) gets the
        # "auditor" Prometheus role label so its AUDIT_* series are
        # attributable in fleet dashboards; inside a serving process the
        # existing primary/replica/standby identity wins
        if not Dashboard.identity().get("role"):
            Dashboard.set_identity(role="auditor")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mv-fleet-auditor")
        self._thread.start()
        log.info("audit: continuous auditor started (%d shard(s), every "
                 "%.1fs)", len(self.primaries), self.interval)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.check()
            except Exception as exc:  # noqa: BLE001 — an auditor that
                # dies on one bad sweep stops watching the fleet
                log.error("audit: sweep failed (%r); retrying next "
                          "interval", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
