"""Periodic JSONL metrics snapshots — the bench/offline-analysis feed.

A :class:`MetricsLogger` thread appends one JSON object per interval to
the ``metrics_path`` file: wall-clock timestamp plus the full dashboard
snapshot (monitors, counters, gauges, histograms as bucket arrays). The
format is what ``bench.py``'s :func:`load_metrics` ingests and what
``make metrics-smoke`` asserts over; ``mv.init`` starts the thread when
the ``metrics_path`` flag is set and ``mv.shutdown`` writes a final
snapshot and stops it.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from multiverso_tpu import log
from multiverso_tpu.dashboard import Dashboard


class MetricsLogger:
    """Append ``{"t": epoch_seconds, ...Dashboard.snapshot()}`` JSONL
    lines every ``interval`` seconds. ``close()`` flushes one final
    snapshot so short-lived sessions still leave a record."""

    def __init__(self, path: str, interval: float = 10.0) -> None:
        self.path = path
        self.interval = max(0.05, float(interval))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mv-metrics-logger")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._write()

    def _write(self) -> None:
        try:
            line = json.dumps({"t": time.time(), **Dashboard.snapshot()})
            with self._lock:
                with open(self.path, "a", encoding="utf-8") as fp:
                    fp.write(line + "\n")
        except Exception as exc:  # noqa: BLE001 — telemetry never kills
            log.error("metrics logger: snapshot to %s failed: %r",
                      self.path, exc)

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._write()  # final snapshot: short sessions still leave data


def load_metrics(path: str) -> List[Dict[str, Any]]:
    """Parse a metrics JSONL file back into snapshot dicts (blank lines
    skipped) — the ingestion half of the format contract."""
    snapshots = []
    with open(path, encoding="utf-8") as fp:
        for line in fp:
            line = line.strip()
            if line:
                snapshots.append(json.loads(line))
    return snapshots
