"""Cross-process trace stitching (the observability plane's spine).

Each process in a fleet — client, shard primaries, read replicas,
standbys, multihost leader — records its half of every traced request in
its own process-global :data:`~multiverso_tpu.obs.trace.TRACES` store,
under the request's wire ``req_id``. The :class:`TraceCollector` pulls
those stores over the slot-free ``Control_Traces`` RPC, estimates each
remote process's clock offset, and merges the per-process hop lists into
end-to-end :class:`StitchedTrace` spans with causally-ordered corrected
timestamps.

Clock correction, spelled out: process wall clocks disagree (NTP skew,
VM drift), so raw ``time_ns`` hops from two processes do not order. For
every req_id recorded by BOTH the local store and a remote store, the
local first hop ``t_l0`` happened before the remote first hop ``t_r0``
(the request had to cross the wire to be recorded there) and the local
last hop ``t_l1`` happened after the remote last hop ``t_r1`` (the reply
had to cross back). The NTP-style estimate

    offset ~ ((t_r0 - t_l0) + (t_r1 - t_l1)) / 2

cancels the transit time to first order when the two legs are
symmetric; the per-process offset is the MEDIAN over all shared req_ids
(robust to the odd retransmitted outlier). Corrected remote timestamps
are ``t_ns - offset``, i.e. everything is expressed on the LOCAL clock.

The collector is a diagnostic reader: it never blocks the data path and
an unreachable endpoint degrades the view (recorded in
:attr:`TraceCollector.unreachable`) rather than failing the collect.
"""

from __future__ import annotations

import statistics
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from multiverso_tpu import config
from multiverso_tpu.obs.trace import DEFAULT_TENANT

LOCAL_PROCESS = "local"


@dataclass
class StitchedTrace:
    """One request's end-to-end span, merged across processes.

    ``hops`` is the causally-ordered list of ``(process, stage,
    t_corrected_ns)`` — corrected onto the collector's local clock.
    ``processes`` is the distinct set of processes the span crossed.
    ``tenant`` is the chargeback label the submit site stamped on the
    span (``_default`` when no store tagged it).
    """

    req_id: int
    hops: List[Tuple[str, str, int]] = field(default_factory=list)
    tenant: str = DEFAULT_TENANT

    @property
    def processes(self) -> List[str]:
        seen: List[str] = []
        for proc, _, _ in self.hops:
            if proc not in seen:
                seen.append(proc)
        return seen

    @property
    def start_ns(self) -> int:
        return self.hops[0][2] if self.hops else 0

    @property
    def duration_ns(self) -> int:
        if len(self.hops) < 2:
            return 0
        return self.hops[-1][2] - self.hops[0][2]

    def stages(self) -> List[str]:
        return [stage for _, stage, _ in self.hops]

    def monotonic(self) -> bool:
        """Corrected timestamps never step backwards (the acceptance
        property of a correctly-stitched span)."""
        times = [t for _, _, t in self.hops]
        return all(a <= b for a, b in zip(times, times[1:]))

    def render(self) -> str:
        """One span, one line per hop, durations relative to the first."""
        if not self.hops:
            return f"trace {self.req_id}: <empty>"
        t0 = self.hops[0][2]
        lines = [f"trace {self.req_id}: {len(self.hops)} hop(s), "
                 f"{self.duration_ns / 1e6:.3f} ms, "
                 f"processes={','.join(self.processes)}"]
        for proc, stage, t in self.hops:
            lines.append(f"  +{(t - t0) / 1e6:9.3f} ms  "
                         f"{proc:<24s} {stage}")
        return "\n".join(lines)


def _normalize(traces: Any) -> Dict[int, List[Tuple[str, int]]]:
    """Wire payloads arrive with STRING req_id keys (the JSON-tree codec
    stringifies int dict keys) and list-shaped hops — normalize both."""
    out: Dict[int, List[Tuple[str, int]]] = {}
    if not isinstance(traces, dict):
        return out
    for key, hops in traces.items():
        try:
            rid = int(key)
        except (TypeError, ValueError):
            continue
        out[rid] = [(str(stage), int(t_ns)) for stage, t_ns in hops]
    return out


def _normalize_tenants(tags: Any) -> Dict[int, str]:
    """The optional ``tenants`` sibling key of a ``Control_Traces``
    payload (same stringified-int-key caveat as the traces dict); legacy
    senders omit it entirely — an absent/misshapen value is just {}."""
    out: Dict[int, str] = {}
    if not isinstance(tags, dict):
        return out
    for key, tenant in tags.items():
        try:
            out[int(key)] = str(tenant)
        except (TypeError, ValueError):
            continue
    return out


def estimate_offset(local: Dict[int, List[Tuple[str, int]]],
                    remote: Dict[int, List[Tuple[str, int]]]
                    ) -> Optional[int]:
    """Median NTP-style clock offset (remote minus local clock, ns) over
    req_ids both stores recorded; None when they share none."""
    samples: List[float] = []
    for rid, r_hops in remote.items():
        l_hops = local.get(rid)
        if not l_hops or not r_hops:
            continue
        t_l0, t_l1 = l_hops[0][1], l_hops[-1][1]
        t_r0, t_r1 = r_hops[0][1], r_hops[-1][1]
        samples.append(((t_r0 - t_l0) + (t_r1 - t_l1)) / 2.0)
    if not samples:
        return None
    return int(statistics.median(samples))


class TraceCollector:
    """Pulls per-process trace stores and stitches cross-process spans.

    ``endpoints`` may be given directly, or discovered from a shard
    layout manifest via :meth:`from_layout` (primaries + replicas +
    the manifest's own endpoint list). ``collect()`` fans requests out
    concurrently (one thread per endpoint, bounded by the per-endpoint
    timeout) and refreshes :attr:`offsets` / :attr:`unreachable`;
    :meth:`stitch` merges the collected stores into
    :class:`StitchedTrace` spans.
    """

    def __init__(self, endpoints: Sequence[str],
                 timeout: Optional[float] = None,
                 include_local: bool = True) -> None:
        # dedupe, keep order: layouts repeat endpoints across roles
        seen: Dict[str, None] = {}
        for ep in endpoints:
            if ep:
                seen.setdefault(str(ep))
        self.endpoints: List[str] = list(seen)
        self.timeout = float(timeout if timeout is not None
                             else config.get_flag("stats_timeout_seconds"))
        self.include_local = bool(include_local)
        # process name -> {req_id: [(stage, t_ns), ...]}
        self.stores: Dict[str, Dict[int, List[Tuple[str, int]]]] = {}
        # process name -> {req_id: tenant} (sparse: default omitted)
        self.tenant_tags: Dict[str, Dict[int, str]] = {}
        # process name -> role string advertised in the reply
        self.roles: Dict[str, str] = {}
        # process name -> estimated clock offset (ns, remote - local)
        self.offsets: Dict[str, int] = {}
        self.unreachable: List[str] = []

    @classmethod
    def from_layout(cls, layout: Dict[str, Any],
                    timeout: Optional[float] = None) -> "TraceCollector":
        """All trace-serving endpoints of a shard-group manifest: every
        shard primary plus every per-shard read replica."""
        eps: List[str] = [str(e) for e in layout.get("endpoints", ())]
        replicas = layout.get("replicas") or {}
        if isinstance(replicas, dict):
            for shard_eps in replicas.values():
                eps.extend(str(e) for e in (shard_eps or ()))
        else:
            for shard_eps in replicas:
                eps.extend(str(e) for e in (shard_eps or ()))
        return cls(eps, timeout=timeout)

    # -- gathering -----------------------------------------------------------
    def collect(self) -> "TraceCollector":
        """Fan one ``Control_Traces`` pull over every endpoint (plus the
        local store), then re-estimate clock offsets. Unreachable
        endpoints land in :attr:`unreachable`, never raise."""
        from multiverso_tpu.runtime.remote import fetch_traces

        results: Dict[str, Optional[Dict[str, Any]]] = {}
        lock = threading.Lock()

        def pull(ep: str) -> None:
            try:
                payload = fetch_traces(ep, timeout=self.timeout)
            except (OSError, RuntimeError) as exc:
                payload = None
                from multiverso_tpu import log
                log.info("trace collector: %s unreachable: %r", ep, exc)
            with lock:
                results[ep] = payload

        threads = [threading.Thread(target=pull, args=(ep,), daemon=True,
                                    name="mv-trace-pull")
                   for ep in self.endpoints]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout + 1.0)

        self.stores.clear()
        self.tenant_tags.clear()
        self.roles.clear()
        self.unreachable = []
        if self.include_local:
            from multiverso_tpu.obs.trace import TRACES
            n = max(1, int(config.get_flag("trace_export_max")))
            self.stores[LOCAL_PROCESS] = _normalize(TRACES.export(n))
            self.tenant_tags[LOCAL_PROCESS] = dict(TRACES.export_tenants(n))
            self.roles[LOCAL_PROCESS] = "client"
        for ep in self.endpoints:
            payload = results.get(ep)
            if payload is None:
                self.unreachable.append(ep)
                continue
            role = str(payload.get("role", "unknown"))
            name = f"{role}@{ep}"
            self.stores[name] = _normalize(payload.get("traces"))
            self.tenant_tags[name] = _normalize_tenants(
                payload.get("tenants"))
            self.roles[name] = role
        self._estimate_offsets()
        return self

    def _estimate_offsets(self) -> None:
        self.offsets = {LOCAL_PROCESS: 0}
        local = self.stores.get(LOCAL_PROCESS, {})
        for name, store in self.stores.items():
            if name == LOCAL_PROCESS:
                continue
            offset = estimate_offset(local, store) if local else None
            # no shared span to estimate from: trust the remote clock
            # (same-host test fleets share one clock anyway)
            self.offsets[name] = 0 if offset is None else offset

    # -- stitching -----------------------------------------------------------
    def stitch(self, req_id: Optional[int] = None) -> List[StitchedTrace]:
        """Merge the collected stores into corrected, causally-ordered
        spans — all of them, or just ``req_id``'s. Sorted by start
        time."""
        rids: Dict[int, None] = {}
        for store in self.stores.values():
            for rid in store:
                if req_id is None or rid == req_id:
                    rids.setdefault(rid)
        spans: List[StitchedTrace] = []
        for rid in rids:
            hops: List[Tuple[str, str, int]] = []
            for name, store in self.stores.items():
                offset = self.offsets.get(name, 0)
                for stage, t_ns in store.get(rid, ()):
                    hops.append((name, stage, int(t_ns) - offset))
            # stable sort: equal corrected times keep per-process
            # recording order (hop lists are append-ordered already)
            hops.sort(key=lambda h: h[2])
            tenant = DEFAULT_TENANT
            for name in self.stores:
                tag = self.tenant_tags.get(name, {}).get(rid)
                if tag and tag != DEFAULT_TENANT:
                    tenant = tag  # first non-default tag wins (the
                    break         # client submit site tags first)
            spans.append(StitchedTrace(req_id=rid, hops=hops,
                                       tenant=tenant))
        spans.sort(key=lambda s: s.start_ns)
        return spans

    def render(self, n: int = 10) -> str:
        """The last ``n`` stitched spans, human-shaped."""
        spans = self.stitch()[-n:]
        head = (f"{len(spans)} stitched trace(s) from "
                f"{len(self.stores)} process(es)")
        if self.unreachable:
            head += f"; unreachable: {', '.join(self.unreachable)}"
        return "\n".join([head] + [s.render() for s in spans])


def collect_traces(endpoints: Sequence[str],
                   timeout: Optional[float] = None,
                   req_id: Optional[int] = None) -> List[StitchedTrace]:
    """One-shot convenience: collect + stitch (``mv.traces``)."""
    collector = TraceCollector(endpoints, timeout=timeout)
    collector.collect()
    return collector.stitch(req_id)
