#include "allocator.h"

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace mvtpu {

namespace {

// Block header layout: [ bucket_or_size | slot offset | atomic refcount ],
// placed in the 32 bytes immediately before the payload. The slot (distance
// from the malloc'd base to the payload) is a multiple of the requested
// alignment so the payload honors alignments > 32 too.
struct Header {
  uint64_t bucket;                // pool bucket (smart) or raw size (default)
  uint32_t slot;                  // payload - slot == malloc'd base
  std::atomic<int> refcount;
};

constexpr size_t kHeaderSlot = 32;  // header room reserved before payload
static_assert(sizeof(Header) <= kHeaderSlot, "header must fit the slot");

inline Header* header_of(char* data) {
  return reinterpret_cast<Header*>(data - kHeaderSlot);
}

inline char* base_of(char* data) { return data - header_of(data)->slot; }

inline char* raw_alloc(size_t payload, size_t alignment) {
  size_t align = alignment < alignof(Header) ? alignof(Header) : alignment;
  if (align < sizeof(void*)) align = sizeof(void*);
  size_t slot = kHeaderSlot > align ? kHeaderSlot : align;
  void* raw = nullptr;
  if (posix_memalign(&raw, align, slot + payload) != 0) {
    throw std::bad_alloc();
  }
  char* data = static_cast<char*>(raw) + slot;
  header_of(data)->slot = static_cast<uint32_t>(slot);
  return data;
}

inline uint64_t bucket_for(size_t size) {
  uint64_t b = 32;
  while (b < size) b <<= 1;
  return b;
}

}  // namespace

char* DefaultAllocator::Alloc(size_t size) {
  char* data = raw_alloc(size, alignment_);
  Header* h = header_of(data);
  h->bucket = size;
  new (&h->refcount) std::atomic<int>(1);
  return data;
}

void DefaultAllocator::Free(char* data) {
  if (data == nullptr) return;
  Header* h = header_of(data);
  if (h->refcount.fetch_sub(1) == 1) {
    std::free(base_of(data));
  }
}

void DefaultAllocator::Refer(char* data) {
  header_of(data)->refcount.fetch_add(1);
}

struct SmartAllocator::Impl {
  size_t alignment;
  std::mutex mutex;
  std::unordered_map<uint64_t, std::vector<char*>> free_lists;
};

SmartAllocator::SmartAllocator(size_t alignment) : impl_(new Impl) {
  impl_->alignment = alignment;
}

SmartAllocator::~SmartAllocator() {
  std::lock_guard<std::mutex> lock(impl_->mutex);
  for (auto& kv : impl_->free_lists) {
    for (char* data : kv.second) {
      std::free(base_of(data));
    }
  }
  delete impl_;
}

char* SmartAllocator::Alloc(size_t size) {
  uint64_t bucket = bucket_for(size);
  {
    std::lock_guard<std::mutex> lock(impl_->mutex);
    auto it = impl_->free_lists.find(bucket);
    if (it != impl_->free_lists.end() && !it->second.empty()) {
      char* data = it->second.back();
      it->second.pop_back();
      pooled_.fetch_sub(1);
      live_.fetch_add(1);
      Header* h = header_of(data);
      h->refcount.store(1);
      return data;
    }
  }
  char* data = raw_alloc(bucket, impl_->alignment);
  Header* h = header_of(data);
  h->bucket = bucket;
  new (&h->refcount) std::atomic<int>(1);
  live_.fetch_add(1);
  return data;
}

void SmartAllocator::Free(char* data) {
  if (data == nullptr) return;
  Header* h = header_of(data);
  if (h->refcount.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lock(impl_->mutex);
    impl_->free_lists[h->bucket].push_back(data);
    live_.fetch_sub(1);
    pooled_.fetch_add(1);
  }
}

void SmartAllocator::Refer(char* data) {
  header_of(data)->refcount.fetch_add(1);
}

Allocator* Allocator::Get() {
  static SmartAllocator instance;
  return &instance;
}

}  // namespace mvtpu

// Flat C exports for the ctypes binding / tests.
extern "C" {

void* MVTPU_Alloc(size_t size) { return mvtpu::Allocator::Get()->Alloc(size); }

void MVTPU_Free(void* data) {
  mvtpu::Allocator::Get()->Free(static_cast<char*>(data));
}

void MVTPU_Refer(void* data) {
  mvtpu::Allocator::Get()->Refer(static_cast<char*>(data));
}

size_t MVTPU_AllocatorLiveBlocks() {
  return static_cast<mvtpu::SmartAllocator*>(mvtpu::Allocator::Get())
      ->live_blocks();
}

size_t MVTPU_AllocatorPooledBlocks() {
  return static_cast<mvtpu::SmartAllocator*>(mvtpu::Allocator::Get())
      ->pooled_blocks();
}

}  // extern "C"
