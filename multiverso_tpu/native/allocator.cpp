#include "allocator.h"

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace mvtpu {

namespace {

// Block header layout: [ bucket_or_size | slot offset | atomic refcount ],
// placed in the 32 bytes immediately before the payload. The slot (distance
// from the malloc'd base to the payload) is a multiple of the requested
// alignment so the payload honors alignments > 32 too.
struct Header {
  uint64_t bucket;                // pool bucket (smart) or raw size (default)
  uint32_t slot;                  // payload - slot == malloc'd base
  std::atomic<int> refcount;
};

constexpr size_t kHeaderSlot = 32;  // header room reserved before payload
static_assert(sizeof(Header) <= kHeaderSlot, "header must fit the slot");

inline Header* header_of(char* data) {
  return reinterpret_cast<Header*>(data - kHeaderSlot);
}

inline char* base_of(char* data) { return data - header_of(data)->slot; }

inline char* raw_alloc(size_t payload, size_t alignment) {
  size_t align = alignment < alignof(Header) ? alignof(Header) : alignment;
  if (align < sizeof(void*)) align = sizeof(void*);
  size_t slot = kHeaderSlot > align ? kHeaderSlot : align;
  void* raw = nullptr;
  if (posix_memalign(&raw, align, slot + payload) != 0) {
    throw std::bad_alloc();
  }
  char* data = static_cast<char*>(raw) + slot;
  header_of(data)->slot = static_cast<uint32_t>(slot);
  return data;
}

inline uint64_t bucket_for(size_t size) {
  uint64_t b = 32;
  while (b < size) b <<= 1;
  return b;
}

}  // namespace

char* DefaultAllocator::Alloc(size_t size) {
  char* data = raw_alloc(size, alignment_);
  Header* h = header_of(data);
  h->bucket = size;
  new (&h->refcount) std::atomic<int>(1);
  live_.fetch_add(1);
  return data;
}

void DefaultAllocator::Free(char* data) {
  if (data == nullptr) return;
  Header* h = header_of(data);
  if (h->refcount.fetch_sub(1) == 1) {
    live_.fetch_sub(1);
    std::free(base_of(data));
  }
}

void DefaultAllocator::Refer(char* data) {
  header_of(data)->refcount.fetch_add(1);
}

struct SmartAllocator::Impl {
  size_t alignment;
  std::mutex mutex;
  std::unordered_map<uint64_t, std::vector<char*>> free_lists;
};

SmartAllocator::SmartAllocator(size_t alignment) : impl_(new Impl) {
  impl_->alignment = alignment;
}

SmartAllocator::~SmartAllocator() {
  std::lock_guard<std::mutex> lock(impl_->mutex);
  for (auto& kv : impl_->free_lists) {
    for (char* data : kv.second) {
      std::free(base_of(data));
    }
  }
  delete impl_;
}

char* SmartAllocator::Alloc(size_t size) {
  uint64_t bucket = bucket_for(size);
  {
    std::lock_guard<std::mutex> lock(impl_->mutex);
    auto it = impl_->free_lists.find(bucket);
    if (it != impl_->free_lists.end() && !it->second.empty()) {
      char* data = it->second.back();
      it->second.pop_back();
      pooled_.fetch_sub(1);
      live_.fetch_add(1);
      Header* h = header_of(data);
      h->refcount.store(1);
      return data;
    }
  }
  char* data = raw_alloc(bucket, impl_->alignment);
  Header* h = header_of(data);
  h->bucket = bucket;
  new (&h->refcount) std::atomic<int>(1);
  live_.fetch_add(1);
  return data;
}

void SmartAllocator::Free(char* data) {
  if (data == nullptr) return;
  Header* h = header_of(data);
  if (h->refcount.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lock(impl_->mutex);
    impl_->free_lists[h->bucket].push_back(data);
    live_.fetch_sub(1);
    pooled_.fetch_add(1);
  }
}

void SmartAllocator::Refer(char* data) {
  header_of(data)->refcount.fetch_add(1);
}

// Singleton configuration: type/alignment are latched by the first Get();
// MVTPU_ConfigureAllocator must run before any allocation (the Python side
// calls it from mv.init() with the allocator_type/allocator_alignment flags).
namespace {
std::mutex g_singleton_mutex;
std::atomic<Allocator*> g_instance{nullptr};
bool g_smart = true;
size_t g_alignment = 16;
}  // namespace

Allocator* Allocator::Get() {
  Allocator* inst = g_instance.load(std::memory_order_acquire);
  if (inst != nullptr) return inst;
  std::lock_guard<std::mutex> lock(g_singleton_mutex);
  inst = g_instance.load(std::memory_order_relaxed);
  if (inst == nullptr) {
    if (g_smart) {
      inst = new SmartAllocator(g_alignment);
    } else {
      inst = new DefaultAllocator(g_alignment);
    }
    g_instance.store(inst, std::memory_order_release);
  }
  return inst;
}

}  // namespace mvtpu

// Flat C exports for the ctypes binding / tests.
extern "C" {

// Returns 0 on success; -1 if the singleton already exists with a different
// configuration (too late to change); -2 on an unknown type string; -3 on an
// alignment posix_memalign would reject (not a power of two >= sizeof(void*))
// — rejected here so a bad flag is a configure error, not a bad_alloc thrown
// across the FFI boundary at first allocation.
int MVTPU_ConfigureAllocator(const char* type, size_t alignment) {
  bool smart;
  if (std::strcmp(type, "smart") == 0) {
    smart = true;
  } else if (std::strcmp(type, "default") == 0) {
    smart = false;
  } else {
    return -2;
  }
  if (alignment < sizeof(void*) || (alignment & (alignment - 1)) != 0) {
    return -3;
  }
  std::lock_guard<std::mutex> lock(mvtpu::g_singleton_mutex);
  if (mvtpu::g_instance.load() != nullptr) {
    return (smart == mvtpu::g_smart && alignment == mvtpu::g_alignment) ? 0
                                                                        : -1;
  }
  mvtpu::g_smart = smart;
  mvtpu::g_alignment = alignment;
  return 0;
}

const char* MVTPU_AllocatorType() {
  std::lock_guard<std::mutex> lock(mvtpu::g_singleton_mutex);
  return mvtpu::g_smart ? "smart" : "default";
}

void* MVTPU_Alloc(size_t size) { return mvtpu::Allocator::Get()->Alloc(size); }

void MVTPU_Free(void* data) {
  mvtpu::Allocator::Get()->Free(static_cast<char*>(data));
}

void MVTPU_Refer(void* data) {
  mvtpu::Allocator::Get()->Refer(static_cast<char*>(data));
}

size_t MVTPU_AllocatorLiveBlocks() {
  return mvtpu::Allocator::Get()->live_blocks();
}

size_t MVTPU_AllocatorPooledBlocks() {
  return mvtpu::Allocator::Get()->pooled_blocks();
}

}  // extern "C"
