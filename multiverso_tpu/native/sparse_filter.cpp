#include "sparse_filter.h"

#include <cstring>

namespace mvtpu {

namespace {
constexpr uint32_t kMagic = 0x4653564D;  // 'MVSF' little-endian

template <typename T>
void append(std::vector<uint8_t>* out, const T& value) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&value);
  out->insert(out->end(), p, p + sizeof(T));
}

template <typename T>
bool read(const uint8_t*& p, const uint8_t* end, T* value) {
  if (p + sizeof(T) > end) return false;
  std::memcpy(value, p, sizeof(T));
  p += sizeof(T);
  return true;
}
}  // namespace

size_t SparseEncode(const float* data, size_t count,
                    std::vector<uint8_t>* out) {
  size_t nnz = 0;
  for (size_t i = 0; i < count; ++i) {
    if (data[i] != 0.0f) ++nnz;
  }
  out->clear();
  bool sparse = nnz * 2 < count;
  append(out, kMagic);
  append(out, static_cast<uint32_t>(sparse ? 1 : 0));
  append(out, static_cast<uint64_t>(count));
  if (!sparse) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(data);
    out->insert(out->end(), p, p + count * sizeof(float));
    return out->size();
  }
  append(out, static_cast<uint64_t>(nnz));
  for (size_t i = 0; i < count; ++i) {
    if (data[i] != 0.0f) {
      append(out, static_cast<uint32_t>(i));
      append(out, data[i]);
    }
  }
  return out->size();
}

// Flat C exports for the ctypes binding (utils/quantization.py).
extern "C" {

size_t MVTPU_SparseEncode(const float* data, size_t count, uint8_t* out,
                          size_t capacity) {
  std::vector<uint8_t> buf;
  size_t n = SparseEncode(data, count, &buf);
  if (n > capacity) return 0;
  std::memcpy(out, buf.data(), n);
  return n;
}

int MVTPU_SparseDecode(const uint8_t* bytes, size_t byte_len, float* data,
                       size_t count) {
  return SparseDecode(bytes, byte_len, data, count) ? 1 : 0;
}

}  // extern "C"

bool SparseDecode(const uint8_t* bytes, size_t byte_len, float* data,
                  size_t count) {
  const uint8_t* p = bytes;
  const uint8_t* end = bytes + byte_len;
  uint32_t magic = 0, kind = 0;
  uint64_t n = 0;
  if (!read(p, end, &magic) || magic != kMagic) return false;
  if (!read(p, end, &kind) || !read(p, end, &n)) return false;
  if (n != count) return false;
  if (kind == 0) {
    if (p + count * sizeof(float) > end) return false;
    std::memcpy(data, p, count * sizeof(float));
    return true;
  }
  uint64_t nnz = 0;
  if (!read(p, end, &nnz)) return false;
  std::memset(data, 0, count * sizeof(float));
  for (uint64_t i = 0; i < nnz; ++i) {
    uint32_t idx = 0;
    float value = 0.0f;
    if (!read(p, end, &idx) || !read(p, end, &value) || idx >= count) {
      return false;
    }
    data[idx] = value;
  }
  return true;
}

}  // namespace mvtpu
