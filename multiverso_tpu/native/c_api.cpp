// C API implementation: embeds CPython and drives the multiverso_tpu runtime.
//
// Design (vs the reference src/c_api.cpp which called the C++ core
// directly): the TPU core IS the JAX runtime, so the shim owns an embedded
// interpreter. All marshalling happens through multiverso_tpu.c_bridge —
// the C side only moves raw pointers wrapped as memoryviews, keeping the
// numpy logic in Python. Every entry point grabs the GIL, so FFI hosts may
// call from any thread.

#include "c_api.h"

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <string>
#include <vector>

namespace {

PyObject* g_bridge = nullptr;  // multiverso_tpu.c_bridge module

void FatalPython(const char* where) {
  std::fprintf(stderr, "[multiverso_tpu c_api] python error in %s:\n", where);
  PyErr_Print();
  std::abort();
}

void EnsureInterpreter() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // Py_InitializeEx leaves the calling thread holding the GIL; release
      // it so other host threads' PyGILState_Ensure can proceed while this
      // thread runs plain C code. Every entry point re-acquires via Gil.
      // The interpreter is deliberately never finalized: tearing down an
      // embedded CPython with JAX/XLA loaded is unsafe, and hosts that
      // MV_ShutDown may keep running.
      PyEval_SaveThread();
    }
  });
}

// RAII GIL hold valid for both embedded and host-owned interpreters.
// Bootstraps the embedded interpreter first: FFI hosts legitimately call
// flag/identity entry points BEFORE MV_Init (the Lua binding's
// mv.set_flag), and PyGILState_Ensure on an uninitialized interpreter is
// a crash (found by native/test_lua_ffi.c).
class Gil {
 public:
  Gil() {
    EnsureInterpreter();
    state_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject* Bridge() {
  if (g_bridge == nullptr) {
    g_bridge = PyImport_ImportModule("multiverso_tpu.c_bridge");
    if (g_bridge == nullptr) FatalPython("import multiverso_tpu.c_bridge");
  }
  return g_bridge;
}

// Call bridge.<name>(args...) and return the result (new ref) or abort.
PyObject* Call(const char* name, PyObject* args) {
  PyObject* fn = PyObject_GetAttrString(Bridge(), name);
  if (fn == nullptr) FatalPython(name);
  PyObject* result = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (result == nullptr) FatalPython(name);
  return result;
}

long CallLong(const char* name) {
  Gil gil;
  PyObject* result = Call(name, nullptr);
  long value = PyLong_AsLong(result);
  Py_DECREF(result);
  return value;
}

PyObject* FloatView(float* data, int size, bool writable) {
  return PyMemoryView_FromMemory(reinterpret_cast<char*>(data),
                                 static_cast<Py_ssize_t>(size) * sizeof(float),
                                 writable ? PyBUF_WRITE : PyBUF_READ);
}

PyObject* IntView(int* data, int size) {
  return PyMemoryView_FromMemory(reinterpret_cast<char*>(data),
                                 static_cast<Py_ssize_t>(size) * sizeof(int),
                                 PyBUF_READ);
}

}  // namespace

extern "C" {

void MV_Init(int* argc, char* argv[]) {
  EnsureInterpreter();
  Gil gil;
  PyObject* list = PyList_New(0);
  int n = (argc != nullptr) ? *argc : 0;
  for (int i = 0; i < n; ++i) {
    PyObject* s = PyUnicode_FromString(argv[i]);
    PyList_Append(list, s);
    Py_DECREF(s);
  }
  PyObject* result = Call("init", Py_BuildValue("(O)", list));
  Py_DECREF(list);
  Py_DECREF(result);
}

void MV_ShutDown() {
  Gil gil;
  Py_DECREF(Call("shutdown", nullptr));
  Py_XDECREF(g_bridge);
  g_bridge = nullptr;  // a later MV_Init re-imports the bridge
}

void MV_Barrier() {
  Gil gil;
  Py_DECREF(Call("barrier", nullptr));
}

int MV_NumWorkers() { return static_cast<int>(CallLong("num_workers")); }
int MV_NumServers() { return static_cast<int>(CallLong("num_servers")); }
int MV_WorkerId() { return static_cast<int>(CallLong("worker_id")); }
int MV_ServerId() { return static_cast<int>(CallLong("server_id")); }
int MV_Rank() { return static_cast<int>(CallLong("rank")); }
int MV_Size() { return static_cast<int>(CallLong("size")); }

void MV_SetFlag(const char* name, const char* value) {
  Gil gil;
  Py_DECREF(Call("set_flag", Py_BuildValue("(ss)", name, value)));
}

// -- array table ------------------------------------------------------------

void MV_NewArrayTable(int size, TableHandler* out) {
  Gil gil;
  PyObject* result = Call("new_array_table", Py_BuildValue("(i)", size));
  *out = reinterpret_cast<TableHandler>(PyLong_AsLong(result));
  Py_DECREF(result);
}

void MV_GetArrayTable(TableHandler handler, float* data, int size) {
  Gil gil;
  PyObject* view = FloatView(data, size, /*writable=*/true);
  Py_DECREF(Call("array_get", Py_BuildValue(
      "(lOi)", reinterpret_cast<long>(handler), view, size)));
  Py_DECREF(view);
}

static void ArrayAdd(TableHandler handler, float* data, int size, int async_) {
  Gil gil;
  PyObject* view = FloatView(data, size, /*writable=*/false);
  Py_DECREF(Call("array_add", Py_BuildValue(
      "(lOii)", reinterpret_cast<long>(handler), view, size, async_)));
  Py_DECREF(view);
}

void MV_AddArrayTable(TableHandler handler, float* data, int size) {
  ArrayAdd(handler, data, size, 0);
}

void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size) {
  ArrayAdd(handler, data, size, 1);
}

// -- matrix table -----------------------------------------------------------

void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out) {
  Gil gil;
  PyObject* result =
      Call("new_matrix_table", Py_BuildValue("(ii)", num_row, num_col));
  *out = reinterpret_cast<TableHandler>(PyLong_AsLong(result));
  Py_DECREF(result);
}

void MV_GetMatrixTableAll(TableHandler handler, float* data, int size) {
  Gil gil;
  PyObject* view = FloatView(data, size, /*writable=*/true);
  Py_DECREF(Call("matrix_get_all", Py_BuildValue(
      "(lOi)", reinterpret_cast<long>(handler), view, size)));
  Py_DECREF(view);
}

static void MatrixAddAll(TableHandler handler, float* data, int size,
                         int async_) {
  Gil gil;
  PyObject* view = FloatView(data, size, /*writable=*/false);
  Py_DECREF(Call("matrix_add_all", Py_BuildValue(
      "(lOii)", reinterpret_cast<long>(handler), view, size, async_)));
  Py_DECREF(view);
}

void MV_AddMatrixTableAll(TableHandler handler, float* data, int size) {
  MatrixAddAll(handler, data, size, 0);
}

void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size) {
  MatrixAddAll(handler, data, size, 1);
}

void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                             int* row_ids, int row_ids_n) {
  Gil gil;
  PyObject* view = FloatView(data, size, /*writable=*/true);
  PyObject* ids = IntView(row_ids, row_ids_n);
  Py_DECREF(Call("matrix_get_rows", Py_BuildValue(
      "(lOiOi)", reinterpret_cast<long>(handler), view, size, ids,
      row_ids_n)));
  Py_DECREF(ids);
  Py_DECREF(view);
}

static void MatrixAddRows(TableHandler handler, float* data, int size,
                          int* row_ids, int row_ids_n, int async_) {
  Gil gil;
  PyObject* view = FloatView(data, size, /*writable=*/false);
  PyObject* ids = IntView(row_ids, row_ids_n);
  Py_DECREF(Call("matrix_add_rows", Py_BuildValue(
      "(lOiOii)", reinterpret_cast<long>(handler), view, size, ids, row_ids_n,
      async_)));
  Py_DECREF(ids);
  Py_DECREF(view);
}

void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                             int* row_ids, int row_ids_n) {
  MatrixAddRows(handler, data, size, row_ids, row_ids_n, 0);
}

void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data, int size,
                                  int* row_ids, int row_ids_n) {
  MatrixAddRows(handler, data, size, row_ids, row_ids_n, 1);
}

}  // extern "C"
