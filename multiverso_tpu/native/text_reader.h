// Native multithreaded libsvm reader — the TPU-era analog of the
// reference's C++ sample readers (Applications/LogisticRegression/src/
// reader.cpp parsed libsvm-style lines on worker threads with async
// buffering). Exposed as a flat C ABI consumed by the Python framework
// via ctypes (models/lr_io.py uses it as the fast path for plain local
// files and falls back to the Python parser for other stream schemes).
#ifndef MULTIVERSO_TPU_TEXT_READER_H_
#define MULTIVERSO_TPU_TEXT_READER_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
  long long n_rows;
  int max_nnz;
  int* labels;    // [n_rows]
  int* indices;   // [n_rows * max_nnz], -1 padded (the Python contract)
  float* values;  // [n_rows * max_nnz], 0 padded
} MVTRResult;

// Parse a libsvm file ("label k:v k:v ..." lines; blank lines skipped;
// a token without ":v" takes value 1.0; tokens beyond max_nnz ignored —
// byte-identical semantics to models/logreg.py::parse_libsvm_line).
// Returns 0 on success; nonzero on IO failure. The result's arrays are
// owned by the library: release with MVTR_FreeResult.
int MVTR_ParseLibsvmFile(const char* path, int max_nnz, MVTRResult* out);
void MVTR_FreeResult(MVTRResult* r);

#ifdef __cplusplus
}
#endif

#endif  // MULTIVERSO_TPU_TEXT_READER_H_
