// See text_reader.h. Design: slurp the file once, split it into chunks at
// newline boundaries, parse chunks on std::thread workers (row order is
// preserved by counting rows per chunk first, then writing each chunk at
// its exclusive-prefix offset), and hand back flat arrays shaped exactly
// like the Python loader's padded batch contract.
//
// Numeric parsing is std::from_chars throughout: locale-independent
// (strtof honors LC_NUMERIC, so an embedding host that called
// setlocale() would silently mis-parse '0.5') and naturally bounded by
// the line end. Any malformed token makes the whole parse return an
// error — the Python caller then falls back to its own parser, which
// raises loudly, so a bad file never trains silently-different data
// depending on whether the .so is built.
#include "text_reader.h"

#include <atomic>
#include <charconv>
#include <climits>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Chunk {
  const char* begin;
  const char* end;
  long long rows = 0;          // live (non-blank) lines
  long long row_offset = 0;    // exclusive prefix sum
};

inline bool is_ws(char c) {
  // match Python str.strip()'s ASCII whitespace (minus '\n', which
  // delimits lines here)
  return c == ' ' || c == '\t' || c == '\r' || c == '\f' || c == '\v';
}

inline const char* skip_ws(const char* p, const char* e) {
  while (p < e && is_ws(*p)) ++p;
  return p;
}

inline bool is_blank(const char* b, const char* e) {
  return skip_ws(b, e) == e;
}

// std::from_chars rejects a leading '+', but Python's int()/float()
// accept one ('+1' is the canonical libsvm positive-label spelling).
// Skip it only when a digit or '.' follows so '++1'/'+-1' still fail
// the native parse and fall back to the loud Python path.
inline const char* skip_plus(const char* p, const char* e) {
  if (p + 1 < e && *p == '+' &&
      ((p[1] >= '0' && p[1] <= '9') || p[1] == '.'))
    return p + 1;
  return p;
}

// Joins already-started threads before any exception propagates: a
// std::thread destroyed while joinable calls std::terminate, which would
// abort the embedding host before MVTR_ParseLibsvmFile's catch(...) runs.
struct ThreadBatch {
  std::vector<std::thread> ts;
  template <typename F>
  void spawn(F&& f) {
    try {
      ts.emplace_back(std::forward<F>(f));
    } catch (...) {
      join_all();
      throw;  // contained by the extern "C" catch, reported as an error
    }
  }
  void join_all() {
    for (auto& t : ts)
      if (t.joinable()) t.join();
  }
  ~ThreadBatch() { join_all(); }
};

long long count_rows(const Chunk& c) {
  long long rows = 0;
  const char* p = c.begin;
  while (p < c.end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(c.end - p)));
    const char* line_end = nl ? nl : c.end;
    if (!is_blank(p, line_end)) ++rows;
    p = nl ? nl + 1 : c.end;
  }
  return rows;
}

// Returns false on any malformed line (caller falls back to Python).
bool parse_chunk(const Chunk& c, int max_nnz, int* labels, int* indices,
                 float* values) {
  long long row = c.row_offset;
  const char* p = c.begin;
  while (p < c.end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(c.end - p)));
    const char* line_end = nl ? nl : c.end;
    if (!is_blank(p, line_end)) {
      const char* cursor = skip_plus(skip_ws(p, line_end), line_end);
      double labelf;
      auto lr = std::from_chars(cursor, line_end, labelf);
      if (lr.ec != std::errc()) return false;  // int(float(tok)) raises
      // nan/inf/out-of-int32-range: Python raises (ValueError/Overflow);
      // a raw cast would be UB — fail so the caller takes the loud path
      if (!std::isfinite(labelf) || labelf >= 2147483648.0 ||
          labelf < -2147483648.0)
        return false;
      labels[row] = static_cast<int>(labelf);
      cursor = lr.ptr;
      int* idx = indices + row * max_nnz;
      float* val = values + row * max_nnz;
      int k = 0;
      while (k < max_nnz) {
        cursor = skip_ws(cursor, line_end);
        if (cursor >= line_end) break;
        cursor = skip_plus(cursor, line_end);
        int feature;
        auto fr = std::from_chars(cursor, line_end, feature);
        if (fr.ec != std::errc()) return false;  // int(k) raises
        cursor = fr.ptr;
        float v = 1.0f;
        if (cursor < line_end && *cursor == ':') {
          ++cursor;
          // "k:" with nothing (or whitespace) next -> 1.0, like the
          // Python `float(v) if v else 1.0` after partition(":")
          if (cursor < line_end && !is_ws(*cursor)) {
            // parse as DOUBLE then narrow: Python computes
            // float32(float64(token)), and from_chars<float> can differ
            // from that double-rounding path by 1 ulp
            cursor = skip_plus(cursor, line_end);
            double vd;
            auto vr = std::from_chars(cursor, line_end, vd);
            if (vr.ec != std::errc()) return false;  // float("abc") raises
            v = static_cast<float>(vd);
            cursor = vr.ptr;
          }
        }
        idx[k] = feature;
        val[k] = v;
        ++k;
      }
      // Python slices parts[1:max_nnz+1]: tokens beyond max_nnz are
      // ignored WITHOUT validation — skip the rest of the line
      ++row;
    }
    p = nl ? nl + 1 : c.end;
  }
  return true;
}

int parse_impl(const char* path, int max_nnz, MVTRResult* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return 2;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return 2; }
  long long size = ftell(f);
  if (size < 0) { fclose(f); return 2; }
  fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size));
  if (size > 0 &&
      fread(buf.data(), 1, static_cast<size_t>(size), f) !=
          static_cast<size_t>(size)) {
    fclose(f);
    return 3;
  }
  fclose(f);

  unsigned nt = std::thread::hardware_concurrency();
  if (nt == 0) nt = 1;
  if (nt > 8) nt = 8;
  const char* base = buf.data();
  const char* end = base + size;
  std::vector<Chunk> chunks;
  const char* cur = base;
  for (unsigned t = 0; t < nt && cur < end; ++t) {
    const char* target =
        (t + 1 == nt) ? end : base + size * (t + 1) / nt;
    if (target < cur) target = cur;
    // extend to the next newline so no line spans two chunks
    const char* nl = target < end
        ? static_cast<const char*>(
              memchr(target, '\n', static_cast<size_t>(end - target)))
        : nullptr;
    const char* stop = nl ? nl + 1 : end;
    chunks.push_back(Chunk{cur, stop});
    cur = stop;
  }

  {  // count pass (parallel)
    ThreadBatch ts;
    for (auto& c : chunks)
      ts.spawn([&c] { c.rows = count_rows(c); });
    ts.join_all();
  }
  long long total = 0;
  for (auto& c : chunks) {
    c.row_offset = total;
    total += c.rows;
  }

  out->n_rows = total;
  out->max_nnz = max_nnz;
  out->labels = static_cast<int*>(malloc(sizeof(int) * total));
  out->indices =
      static_cast<int*>(malloc(sizeof(int) * total * max_nnz));
  out->values =
      static_cast<float*>(malloc(sizeof(float) * total * max_nnz));
  if (total > 0 && (!out->labels || !out->indices || !out->values)) {
    MVTR_FreeResult(out);
    return 4;
  }
  // int32 -1 is all-0xFF bytes: one memset instead of a serial loop
  memset(out->indices, 0xFF, sizeof(int) * total * max_nnz);
  memset(out->values, 0, sizeof(float) * total * max_nnz);

  std::atomic<bool> ok{true};
  {  // parse pass (parallel; disjoint output ranges per chunk)
    ThreadBatch ts;
    for (auto& c : chunks)
      ts.spawn([&c, max_nnz, out, &ok] {
        if (!parse_chunk(c, max_nnz, out->labels, out->indices,
                         out->values))
          ok.store(false, std::memory_order_relaxed);
      });
    ts.join_all();
  }
  if (!ok.load()) {
    MVTR_FreeResult(out);
    return 5;  // malformed input: caller uses the (loud) Python path
  }
  return 0;
}

}  // namespace

extern "C" int MVTR_ParseLibsvmFile(const char* path, int max_nnz,
                                    MVTRResult* out) {
  if (!path || !out || max_nnz <= 0) return 1;
  out->n_rows = 0;
  out->labels = nullptr;
  out->indices = nullptr;
  out->values = nullptr;
  try {
    return parse_impl(path, max_nnz, out);
  } catch (...) {
    // bad_alloc (file larger than RAM) / thread spawn failure must not
    // cross the C ABI and abort the embedding host — report and let the
    // caller fall back to the streaming Python reader
    MVTR_FreeResult(out);
    return 6;
  }
}

extern "C" void MVTR_FreeResult(MVTRResult* r) {
  if (!r) return;
  free(r->labels);
  free(r->indices);
  free(r->values);
  r->labels = nullptr;
  r->indices = nullptr;
  r->values = nullptr;
  r->n_rows = 0;
}
