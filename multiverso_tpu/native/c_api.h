// Flat C API for FFI hosts (Lua/C#/C legacy clients).
//
// Capability parity with the reference's extern "C" surface
// (include/multiverso/c_api.h:14-54): float-only Array/Matrix tables plus
// init/shutdown/barrier/identity. Implementation embeds CPython and drives
// the TPU runtime (multiverso_tpu) in-process, so an unmodified reference
// client links against libmultiverso_tpu.so and its tables land in TPU HBM.
#ifndef MULTIVERSO_TPU_C_API_H_
#define MULTIVERSO_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void* TableHandler;

// -- lifecycle --------------------------------------------------------------
void MV_Init(int* argc, char* argv[]);
void MV_ShutDown();
void MV_Barrier();

// -- identity ---------------------------------------------------------------
int MV_NumWorkers();
int MV_NumServers();
int MV_WorkerId();
int MV_ServerId();
int MV_Rank();
int MV_Size();

// -- flags ------------------------------------------------------------------
void MV_SetFlag(const char* name, const char* value);

// -- array table (float) ----------------------------------------------------
void MV_NewArrayTable(int size, TableHandler* out);
void MV_GetArrayTable(TableHandler handler, float* data, int size);
void MV_AddArrayTable(TableHandler handler, float* data, int size);
void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size);

// -- matrix table (float) ---------------------------------------------------
void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
void MV_GetMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size);
void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                             int* row_ids, int row_ids_n);
void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                             int* row_ids, int row_ids_n);
void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data, int size,
                                  int* row_ids, int row_ids_n);

#ifdef __cplusplus
}
#endif

#endif  // MULTIVERSO_TPU_C_API_H_
