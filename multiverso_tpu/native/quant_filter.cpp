// Quantized delta pack/unpack — the native half of the OneBits-slot codec
// (utils/quantization.py owns the header + scale derivation; this is the
// hot O(n) bit packing). Byte-identical to the numpy fallback: float32
// elementwise math with nearbyintf (round-half-to-even matches np.rint),
// little-endian code order within each byte.
//
// Reference capability (not copied): OneBitsFilter was an empty stub
// (include/multiverso/util/quantization_util.h:160-161); the reference's
// quantization story never shipped. Implemented TPU-era: client-side
// error feedback lives in Python, this file only moves bits.
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// Pack n float32 values at `bits` (1|2|4|8) per value into out
// (ceil(n*(8/bits)) bytes, caller-zeroed). q = clip(rint((x-lo)*inv), 0,
// 2^bits-1); codes fill each byte from its low bits upward.
void MVTPU_QuantPack(const float* x, size_t n, float lo, float inv,
                     int bits, uint8_t* out) {
  const int per_byte = 8 / bits;
  const float levels = static_cast<float>((1 << bits) - 1);
  for (size_t i = 0; i < n; ++i) {
    float q = nearbyintf((x[i] - lo) * inv);
    if (q < 0.0f) q = 0.0f;
    if (q > levels) q = levels;
    const unsigned code = static_cast<unsigned>(q);
    out[i / per_byte] |=
        static_cast<uint8_t>(code << (bits * (i % per_byte)));
  }
}

// Unpack n codes back to float32: x = lo + q*step.
void MVTPU_QuantUnpack(const uint8_t* in, size_t n, float lo, float step,
                       int bits, float* out) {
  const int per_byte = 8 / bits;
  const unsigned mask = (1u << bits) - 1u;
  for (size_t i = 0; i < n; ++i) {
    const unsigned code = (in[i / per_byte] >> (bits * (i % per_byte))) & mask;
    out[i] = lo + static_cast<float>(code) * step;
  }
}

}  // extern "C"
