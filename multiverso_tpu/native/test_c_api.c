/* End-to-end C client of libmultiverso_tpu.so — the FFI parity proof.
 *
 * Mirrors the reference's MPI end-to-end tests (Test/test_array_table.cpp,
 * test_matrix_table.cpp) driven purely through the flat C API: init, array
 * add/get, matrix whole and row ops, async add + barrier, identity queries.
 * Exit code 0 = all assertions passed.
 */
#include <stdio.h>
#include <stdlib.h>
#include <math.h>

#include "c_api.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__, \
              #cond);                                                   \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

int main(int argc, char* argv[]) {
  MV_Init(&argc, argv);
  CHECK(MV_NumWorkers() >= 1);
  CHECK(MV_WorkerId() >= 0);
  CHECK(MV_NumServers() >= 1);
  CHECK(MV_Rank() == 0);

  /* array table: two adds then get */
  TableHandler array;
  MV_NewArrayTable(64, &array);
  float delta[64], out[64];
  for (int i = 0; i < 64; ++i) delta[i] = (float)i;
  MV_AddArrayTable(array, delta, 64);
  MV_AddArrayTable(array, delta, 64);
  MV_GetArrayTable(array, out, 64);
  for (int i = 0; i < 64; ++i) CHECK(fabsf(out[i] - 2.0f * i) < 1e-5f);

  /* async add then barrier-ish get */
  MV_AddAsyncArrayTable(array, delta, 64);
  MV_Barrier();
  MV_GetArrayTable(array, out, 64);
  for (int i = 0; i < 64; ++i) CHECK(fabsf(out[i] - 3.0f * i) < 1e-4f);

  /* matrix table: whole add/get + row ops */
  TableHandler matrix;
  MV_NewMatrixTable(10, 4, &matrix);
  float mdelta[40], mout[40];
  for (int i = 0; i < 40; ++i) mdelta[i] = 1.0f;
  MV_AddMatrixTableAll(matrix, mdelta, 40);
  MV_GetMatrixTableAll(matrix, mout, 40);
  for (int i = 0; i < 40; ++i) CHECK(fabsf(mout[i] - 1.0f) < 1e-5f);

  int rows[2] = {3, 7};
  float rdelta[8] = {5, 5, 5, 5, 9, 9, 9, 9};
  float rout[8];
  MV_AddMatrixTableByRows(matrix, rdelta, 8, rows, 2);
  MV_GetMatrixTableByRows(matrix, rout, 8, rows, 2);
  CHECK(fabsf(rout[0] - 6.0f) < 1e-5f);
  CHECK(fabsf(rout[4] - 10.0f) < 1e-5f);

  MV_ShutDown();
  printf("c_api smoke test passed\n");
  return 0;
}
