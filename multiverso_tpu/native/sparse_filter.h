// Wire sparsification codec — capability parity with the reference's
// quantization filters.
//
// Reference capability (not copied): SparseFilter<data,index> encodes a
// float payload as (index, value) pairs when more than half the entries are
// zero, with a size side-channel so the receiver knows whether the blob is
// compressed (include/multiverso/util/quantization_util.h:37-154).
//
// TPU-era role: compression only matters on HOST hops (the C-API / external
// client bridge) — on-mesh traffic is XLA's business. Format:
//   [u32 magic 'MVSF'][u32 kind 0=dense,1=sparse][u64 count]
//   dense:  count * f32
//   sparse: [u64 nnz] nnz * (u32 index, f32 value)
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace mvtpu {

// Returns the encoded byte size written to `out` (resized as needed).
// Chooses the sparse form when strictly less than half the values are
// nonzero, dense otherwise.
size_t SparseEncode(const float* data, size_t count, std::vector<uint8_t>* out);

// Decodes into `data` (must hold `count` floats). Returns false on a
// malformed payload or count mismatch.
bool SparseDecode(const uint8_t* bytes, size_t byte_len, float* data,
                  size_t count);

}  // namespace mvtpu
