// Host-side pooled allocator — capability parity with the reference L0 layer.
//
// Reference capability (not copied): aligned malloc with a header-embedded
// atomic refcount, plus a "smart" size-bucketed (pow2, >=32B) free-list pool
// (include/multiverso/util/allocator.h, src/util/allocator.cpp).
//
// TPU-era role: the device data path allocates through XLA; this pool backs
// the HOST side of the C-API bridge (staging buffers for Get/Add payloads
// crossing the FFI boundary) where malloc churn at high request rates would
// otherwise dominate.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace mvtpu {

class Allocator {
 public:
  virtual ~Allocator() = default;
  virtual char* Alloc(size_t size) = 0;
  virtual void Free(char* data) = 0;
  virtual void Refer(char* data) = 0;
  virtual size_t live_blocks() const { return 0; }
  virtual size_t pooled_blocks() const { return 0; }
  // Singleton keyed on the allocator_type / allocator_alignment flags,
  // plumbed from the Python registry via MVTPU_ConfigureAllocator before
  // first use (reference: MV_CONFIG_allocator_type, allocator.cpp:153).
  static Allocator* Get();
};

// Plain aligned allocator: header { atomic<int> refcount } before payload;
// Free releases memory immediately (no pooling).
class DefaultAllocator : public Allocator {
 public:
  explicit DefaultAllocator(size_t alignment = 16) : alignment_(alignment) {}
  char* Alloc(size_t size) override;
  void Free(char* data) override;
  void Refer(char* data) override;
  size_t live_blocks() const override { return live_.load(); }

 private:
  size_t alignment_;
  std::atomic<size_t> live_{0};
};

// Size-bucketed pool: blocks are rounded up to powers of two (>= 32B) and
// recycled through per-bucket LIFO free lists.
class SmartAllocator : public Allocator {
 public:
  explicit SmartAllocator(size_t alignment = 16);
  ~SmartAllocator() override;
  char* Alloc(size_t size) override;
  void Free(char* data) override;
  void Refer(char* data) override;

  size_t live_blocks() const override { return live_.load(); }
  size_t pooled_blocks() const override { return pooled_.load(); }

 private:
  struct Impl;
  Impl* impl_;
  std::atomic<size_t> live_{0};
  std::atomic<size_t> pooled_{0};
};

}  // namespace mvtpu
