/* Lua-FFI replay harness: executes the EXACT call sequence
 * bindings/lua/multiverso.lua makes against libmultiverso_tpu.so, the way
 * LuaJIT's FFI would make it — dlopen + dlsym (ffi.load resolves symbols
 * dynamically, never at link time), per-call heap buffers (ffi.new
 * allocates zero-initialized cdata per call), NULL-terminated argv with a
 * heap char buffer per string (mv.init), int[] row-id arrays built from
 * Lua tables (MatrixTableHandler:get/add), and the async-by-default add
 * dispatch (opts.sync selects the blocking spelling).
 *
 * On top of the marshalling replay it runs the reference Lua binding's
 * end-to-end workload shape — an XOR net trained with its parameters
 * living in an ArrayTable (capability match for
 * /root/reference/binding/lua/xor.lua, not a translation): every
 * iteration Gets the parameters over the FFI, computes gradients in
 * plain C, and Adds the scaled delta back. Exit 0 = marshalling AND
 * learning both verified.
 *
 * Each section is annotated with the multiverso.lua lines it replays so
 * the harness fails if the binding's sequence drifts from the C ABI.
 */
#include <dlfcn.h>
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__, \
              #cond);                                                   \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

typedef void* TableHandler;

/* the cdef'd surface (multiverso.lua:22-48), resolved like ffi.load */
static void (*MV_Init)(int*, char*[]);
static void (*MV_ShutDown)(void);
static void (*MV_Barrier)(void);
static int (*MV_NumWorkers)(void);
static int (*MV_NumServers)(void);
static int (*MV_WorkerId)(void);
static int (*MV_ServerId)(void);
static int (*MV_Rank)(void);
static int (*MV_Size)(void);
static void (*MV_SetFlag)(const char*, const char*);
static void (*MV_NewArrayTable)(int, TableHandler*);
static void (*MV_GetArrayTable)(TableHandler, float*, int);
static void (*MV_AddArrayTable)(TableHandler, float*, int);
static void (*MV_AddAsyncArrayTable)(TableHandler, float*, int);
static void (*MV_NewMatrixTable)(int, int, TableHandler*);
static void (*MV_GetMatrixTableAll)(TableHandler, float*, int);
static void (*MV_AddMatrixTableAll)(TableHandler, float*, int);
static void (*MV_AddAsyncMatrixTableAll)(TableHandler, float*, int);
static void (*MV_GetMatrixTableByRows)(TableHandler, float*, int, int*, int);
static void (*MV_AddMatrixTableByRows)(TableHandler, float*, int, int*, int);
static void (*MV_AddAsyncMatrixTableByRows)(TableHandler, float*, int, int*,
                                            int);

static void* must_sym(void* lib, const char* name) {
  void* p = dlsym(lib, name);
  if (!p) {
    fprintf(stderr, "dlsym(%s) failed: %s\n", name, dlerror());
    exit(1);
  }
  return p;
}

/* mv.init (multiverso.lua:56-69): argc as int[1], argv as a
 * zero-initialized char*[#args+1] (ffi.new zero-fills -> NULL
 * terminator), each string copied into its own heap char buffer. */
static void lua_init(int nargs, const char** args) {
  int* argc = calloc(1, sizeof(int));
  char** argv = calloc((size_t)nargs + 1, sizeof(char*));
  *argc = nargs;
  for (int i = 0; i < nargs; ++i) {
    size_t len = strlen(args[i]);
    char* buf = calloc(len + 1, 1); /* ffi.new('char[?]', #a+1, a) */
    memcpy(buf, args[i], len);
    argv[i] = buf;
  }
  MV_Init(argc, argv);
  for (int i = 0; i < nargs; ++i) free(argv[i]);
  free(argv);
  free(argc);
}

/* -- XOR workload (capability shape of binding/lua/xor.lua) ------------- */

#define NH 4 /* hidden units: wide enough that random init escapes the
               * OR/AND local minima a 2-unit XOR net falls into */
#define NPARAM (NH * 2 + NH + NH + 1) /* w1(2xNH) b1(NH) w2(NH) b2(1) */

static float fwd(const float* p, const float* x, float* h) {
  const float* w1 = p;            /* [NH][2] */
  const float* b1 = p + 2 * NH;   /* [NH] */
  const float* w2 = b1 + NH;      /* [NH] */
  float b2 = w2[NH];
  float z = b2;
  for (int j = 0; j < NH; ++j) {
    h[j] = tanhf(w1[2 * j] * x[0] + w1[2 * j + 1] * x[1] + b1[j]);
    z += w2[j] * h[j];
  }
  return 1.0f / (1.0f + expf(-z));
}

static void xor_grad(const float* p, float* g) {
  static const float X[4][2] = {{0, 0}, {0, 1}, {1, 0}, {1, 1}};
  static const float Y[4] = {0, 1, 1, 0};
  const float* w2 = p + 3 * NH;
  memset(g, 0, NPARAM * sizeof(float));
  for (int s = 0; s < 4; ++s) {
    float h[NH];
    float y = fwd(p, X[s], h);
    float dz = y - Y[s]; /* d(BCE)/dz for sigmoid output */
    for (int j = 0; j < NH; ++j) {
      float dh = dz * w2[j] * (1 - h[j] * h[j]);
      g[2 * j] += dh * X[s][0];
      g[2 * j + 1] += dh * X[s][1];
      g[2 * NH + j] += dh;       /* b1 */
      g[3 * NH + j] += dz * h[j]; /* w2 */
    }
    g[4 * NH] += dz; /* b2 */
  }
}

int main(void) {
  /* ffi.load('multiverso_tpu') -> the .so next to this binary */
  void* lib = dlopen("./libmultiverso_tpu.so", RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen failed: %s\n", dlerror());
    return 1;
  }
  MV_Init = must_sym(lib, "MV_Init");
  MV_ShutDown = must_sym(lib, "MV_ShutDown");
  MV_Barrier = must_sym(lib, "MV_Barrier");
  MV_NumWorkers = must_sym(lib, "MV_NumWorkers");
  MV_NumServers = must_sym(lib, "MV_NumServers");
  MV_WorkerId = must_sym(lib, "MV_WorkerId");
  MV_ServerId = must_sym(lib, "MV_ServerId");
  MV_Rank = must_sym(lib, "MV_Rank");
  MV_Size = must_sym(lib, "MV_Size");
  MV_SetFlag = must_sym(lib, "MV_SetFlag");
  MV_NewArrayTable = must_sym(lib, "MV_NewArrayTable");
  MV_GetArrayTable = must_sym(lib, "MV_GetArrayTable");
  MV_AddArrayTable = must_sym(lib, "MV_AddArrayTable");
  MV_AddAsyncArrayTable = must_sym(lib, "MV_AddAsyncArrayTable");
  MV_NewMatrixTable = must_sym(lib, "MV_NewMatrixTable");
  MV_GetMatrixTableAll = must_sym(lib, "MV_GetMatrixTableAll");
  MV_AddMatrixTableAll = must_sym(lib, "MV_AddMatrixTableAll");
  MV_AddAsyncMatrixTableAll = must_sym(lib, "MV_AddAsyncMatrixTableAll");
  MV_GetMatrixTableByRows = must_sym(lib, "MV_GetMatrixTableByRows");
  MV_AddMatrixTableByRows = must_sym(lib, "MV_AddMatrixTableByRows");
  MV_AddAsyncMatrixTableByRows = must_sym(lib, "MV_AddAsyncMatrixTableByRows");

  /* mv.set_flag before init (multiverso.lua:79, tostring coercion) */
  MV_SetFlag("local_workers", "1");
  lua_init(0, NULL);
  CHECK(MV_NumWorkers() >= 1);
  CHECK(MV_NumServers() >= 1);
  CHECK(MV_WorkerId() >= 0);
  CHECK(MV_ServerId() == 0); /* default role: this process is the server */
  CHECK(MV_Rank() == 0);
  CHECK(MV_Size() == 1);

  /* ArrayTableHandler:new(size) (multiverso.lua:107-113): handler out
   * param as TableHandler[1] */
  TableHandler* out = calloc(1, sizeof(TableHandler));
  MV_NewArrayTable(NPARAM, out);
  TableHandler params_tbl = out[0];
  free(out);

  /* seed the parameters once (deterministic srand: xor.lua seeded torch) */
  srand(7);
  float init[NPARAM];
  for (int i = 0; i < NPARAM; ++i)
    init[i] = ((float)rand() / RAND_MAX - 0.5f) * 2.0f;
  MV_AddArrayTable(params_tbl, init, NPARAM); /* opts.sync=true spelling */

  /* training loop: tbl:get() -> grads in C -> tbl:add(delta) async, the
   * xor.lua epoch shape; per-iteration heap buffers like ffi.new */
  const float lr = 0.8f;
  for (int it = 0; it < 600; ++it) {
    float* buf = calloc(NPARAM, sizeof(float)); /* ffi.new('float[?]') */
    MV_GetArrayTable(params_tbl, buf, NPARAM);
    float g[NPARAM], delta[NPARAM];
    xor_grad(buf, g);
    for (int i = 0; i < NPARAM; ++i) delta[i] = -lr * g[i];
    if (it % 2 == 0)
      MV_AddArrayTable(params_tbl, delta, NPARAM); /* {sync=true} */
    else
      MV_AddAsyncArrayTable(params_tbl, delta, NPARAM); /* default */
    free(buf);
  }
  MV_Barrier(); /* mv.barrier() drains the async tail (xor.lua epoch end) */

  float trained[NPARAM];
  MV_GetArrayTable(params_tbl, trained, NPARAM);
  static const float X[4][2] = {{0, 0}, {0, 1}, {1, 0}, {1, 1}};
  static const float Y[4] = {0, 1, 1, 0};
  for (int s = 0; s < 4; ++s) {
    float h[NH];
    float y = fwd(trained, X[s], h);
    fprintf(stderr, "xor(%g,%g) = %.3f want %g\n", X[s][0], X[s][1], y, Y[s]);
    CHECK(fabsf(y - Y[s]) < 0.35f);
  }

  /* MatrixTableHandler replay (multiverso.lua:136-176): whole get/add,
   * row-subset get/add with int[] ids from a Lua table, async rows */
  out = calloc(1, sizeof(TableHandler));
  MV_NewMatrixTable(6, 3, out);
  TableHandler mat = out[0];
  free(out);

  float* mdelta = calloc(18, sizeof(float));
  for (int i = 0; i < 18; ++i) mdelta[i] = 0.5f;
  MV_AddMatrixTableAll(mat, mdelta, 18); /* {sync=true} */
  float* mout = calloc(18, sizeof(float));
  MV_GetMatrixTableAll(mat, mout, 18);
  for (int i = 0; i < 18; ++i) CHECK(fabsf(mout[i] - 0.5f) < 1e-5f);
  free(mdelta);
  free(mout);

  int* ids = calloc(2, sizeof(int)); /* ffi.new('int[?]', #row_ids, ...) */
  ids[0] = 1;
  ids[1] = 4;
  float* rdelta = calloc(6, sizeof(float));
  for (int i = 0; i < 6; ++i) rdelta[i] = (float)(i + 1);
  MV_AddMatrixTableByRows(mat, rdelta, 6, ids, 2);
  MV_AddAsyncMatrixTableByRows(mat, rdelta, 6, ids, 2);
  MV_Barrier();
  float* rout = calloc(6, sizeof(float));
  MV_GetMatrixTableByRows(mat, rout, 6, ids, 2);
  for (int i = 0; i < 6; ++i)
    CHECK(fabsf(rout[i] - (0.5f + 2.0f * (i + 1))) < 1e-4f);
  free(ids);
  free(rdelta);
  free(rout);

  /* async whole-matrix add (MatrixTableHandler:add default spelling) */
  float* adelta = calloc(18, sizeof(float));
  for (int i = 0; i < 18; ++i) adelta[i] = 0.25f;
  MV_AddAsyncMatrixTableAll(mat, adelta, 18);
  MV_Barrier(); /* drain the async tail before reading */
  float* aout = calloc(18, sizeof(float));
  MV_GetMatrixTableAll(mat, aout, 18);
  CHECK(fabsf(aout[0] - 0.75f) < 1e-4f); /* 0.5 (sync all) + 0.25 */
  free(adelta);
  free(aout);

  MV_ShutDown();
  printf("lua ffi replay passed\n");
  return 0;
}
