// Blocking MPMC queue with exit poison — native twin of the Python
// multiverso_tpu.utils.MtQueue (reference capability:
// include/multiverso/util/mt_queue.h). Header-only building block for
// native hosts; the C-API bridge currently delegates async Adds to the
// Python-side queue and does not use this yet.
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>

namespace mvtpu {

template <typename T>
class MtQueue {
 public:
  void Push(T item) {
    {
      std::lock_guard<std::mutex> lock(mutex_);
      items_.push_back(std::move(item));
    }
    nonempty_.notify_one();
  }

  // Blocking pop; returns false once Exit() was called and the queue drained.
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lock(mutex_);
    nonempty_.wait(lock, [this] { return !items_.empty() || !alive_; });
    if (items_.empty()) return false;
    *out = std::move(items_.front());
    items_.pop_front();
    return true;
  }

  bool TryPop(T* out) {
    std::lock_guard<std::mutex> lock(mutex_);
    if (items_.empty()) return false;
    *out = std::move(items_.front());
    items_.pop_front();
    return true;
  }

  size_t Size() const {
    std::lock_guard<std::mutex> lock(mutex_);
    return items_.size();
  }

  bool Empty() const { return Size() == 0; }

  void Exit() {
    {
      std::lock_guard<std::mutex> lock(mutex_);
      alive_ = false;
    }
    nonempty_.notify_all();
  }

  bool Alive() const {
    std::lock_guard<std::mutex> lock(mutex_);
    return alive_;
  }

 private:
  mutable std::mutex mutex_;
  std::condition_variable nonempty_;
  std::deque<T> items_;
  bool alive_ = true;
};

}  // namespace mvtpu
