"""The autopilot's safety interlock: integrity beats optimization.

A controller that reshapes a fleet whose replicas disagree about state
is a controller amplifying corruption — so the interlock latches the
autopilot FROZEN the moment the integrity plane reports divergence, and
nothing but an explicit operator acknowledgement unfreezes it:

* every :meth:`check` consults the queryable auditor state
  (``FleetAuditor.divergent``) AND the process-local
  ``AUDIT_DIVERGENCE`` counter (so an auditor running in this process
  but not handed to the interlock still trips it);
* the freeze is LATCHING: the auditor's flag auto-clears on a clean
  sweep, but a fleet that diverged and "recovered" unsupervised still
  needs a human to decide the surviving state is the right one;
* :meth:`ack` is the only unfreeze. It re-baselines the divergence
  counter and clears the latch — and if divergence persists, the very
  next check freezes again (an ack is consent to resume, not a mute).

Freeze/unfreeze transitions count ``AUTOPILOT_FREEZES`` /
``AUTOPILOT_ACKS``, hold the ``AUTOPILOT_FROZEN`` gauge (the operator's
dashboard bit), and drop ``autopilot_frozen`` / ``autopilot_ack``
flight-recorder dumps carrying the trigger and the auditor's report —
the runbook in docs/autopilot.md starts from that dump.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from multiverso_tpu import log
from multiverso_tpu.dashboard import Dashboard, count, gauge_set
from multiverso_tpu.obs.trace import flight_dump


class SafetyInterlock:
    """Latching divergence interlock between policy and actuators."""

    def __init__(self, auditor: Any = None) -> None:
        self.auditor = auditor
        self.frozen = False
        self.frozen_since: Optional[float] = None
        self.freeze_reason: str = ""
        # divergences seen before the autopilot existed are the
        # operator's business, not grounds to refuse to start
        self._baseline = Dashboard.counter_value("AUDIT_DIVERGENCE")

    def check(self) -> bool:
        """May the autopilot act this tick? False while frozen; freezes
        (and returns False) when the integrity plane reports divergence."""
        if self.frozen:
            return False
        seen = Dashboard.counter_value("AUDIT_DIVERGENCE")
        if seen > self._baseline:
            self.freeze(f"AUDIT_DIVERGENCE counter advanced "
                        f"({self._baseline} -> {seen})")
            return False
        if self.auditor is not None and \
                getattr(self.auditor, "divergent", False):
            self.freeze("fleet auditor reports live divergence")
            return False
        return True

    def freeze(self, reason: str) -> None:
        """Latch the autopilot frozen (idempotent)."""
        if self.frozen:
            return
        self.frozen = True
        self.frozen_since = time.time()
        self.freeze_reason = str(reason)
        count("AUTOPILOT_FREEZES")
        gauge_set("AUTOPILOT_FROZEN", 1)
        status = (self.auditor.status()
                  if self.auditor is not None
                  and hasattr(self.auditor, "status") else None)
        # "reason" is the dump's event name — the trigger text rides as
        # "why" so the renderer can't clobber it
        flight_dump("autopilot_frozen", why=self.freeze_reason,
                    audit_status=status)
        log.error("autopilot: FROZEN — %s (unfreeze requires an "
                  "operator ack; docs/autopilot.md runbook)", reason)

    def ack(self, operator: str = "operator") -> None:
        """The explicit operator acknowledgement — the ONLY unfreeze.
        Re-baselines the divergence counter; if divergence persists the
        next check() freezes again immediately."""
        self._baseline = Dashboard.counter_value("AUDIT_DIVERGENCE")
        was = self.frozen
        self.frozen = False
        self.frozen_since = None
        reason, self.freeze_reason = self.freeze_reason, ""
        if was:
            count("AUTOPILOT_ACKS")
            gauge_set("AUTOPILOT_FROZEN", 0)
            flight_dump("autopilot_ack", operator=str(operator),
                        cleared=reason)
            log.info("autopilot: unfrozen by %s (was: %s)", operator,
                     reason)

    def status(self) -> Dict[str, Any]:
        return {"frozen": self.frozen,
                "frozen_since": self.frozen_since,
                "reason": self.freeze_reason,
                "divergence_baseline": self._baseline}
