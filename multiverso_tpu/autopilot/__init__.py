"""Fleet autopilot: the control plane that acts on its own telemetry.

Everything this loop touches existed before it — the hot-range detector
proposes splits, the MigrationCoordinator migrates, ShardGroup grows
and shrinks replica fleets, the tiered store obeys a byte budget, the
SLO engine and the fleet auditor watch — but nothing connected sensing
to acting. The :class:`Autopilot` closes that loop on a fixed cadence:

    sense  -> one FleetSense snapshot of the telemetry plane
    decide -> the policy's single gated Decision (or "none")
    check  -> the safety interlock (any AUDIT_DIVERGENCE = frozen)
    act    -> the actuators (crash-safe machinery underneath)
    record -> decision + rejected alternatives + hysteresis/cooldown
              state + outcome, into the flight recorder

Control theory for distributed storage, sized for this codebase: Li et
al.'s dynamic server membership (OSDI'14) driven by a diurnal load
curve instead of an operator, with Dean & Barroso's hedging telemetry
as the replica-scaling signal.

Safety invariants (docs/autopilot.md):

* the interlock is consulted EVERY tick before the policy runs; frozen
  means no action, and only an operator ``ack()`` unfreezes;
* every action dispatches to machinery that already guarantees zero
  acked-Add loss on its own (migration fencing, atomic manifest
  republish) — the autopilot adds judgement, never a new write path;
* a controller death mid-action (``MV_AUTOPILOT_KILL``) latches the
  loop frozen; the fleet it leaves behind is consistent because the
  layer below was.

``mv.autopilot(group)`` is the operator entrypoint; ``tick_now()`` is
the deterministic seam the drills and tests drive.
"""

from __future__ import annotations

import sys
import threading
import time
import types
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from multiverso_tpu import config, log
from multiverso_tpu.autopilot.actuators import Actuators, AutopilotKilled
from multiverso_tpu.autopilot.interlock import SafetyInterlock
from multiverso_tpu.autopilot.policy import AutopilotPolicy, Decision
from multiverso_tpu.autopilot.sensors import FleetSense, FleetSensors
from multiverso_tpu.dashboard import count, gauge_set
from multiverso_tpu.obs.trace import flight_dump

__all__ = ["Autopilot", "AutopilotKilled", "Actuators", "AutopilotPolicy",
           "Decision", "FleetSense", "FleetSensors", "SafetyInterlock"]


class Autopilot:
    """The periodic control loop over one live ShardGroup.

    Components are injectable (tests swap fakes in); by default the
    loop builds a HotRangeDetector over the group's shard count, a
    FleetSensors over the global recorder, and a policy/actuator pair
    from the ``autopilot_*`` flags. ``interval`` <= 0 builds the loop
    without a thread — ``tick_now()`` drives it deterministically."""

    def __init__(self, group: Any,
                 interval: Optional[float] = None,
                 recorder: Any = None,
                 engine: Any = None,
                 auditor: Any = None,
                 detector: Any = None,
                 sensors: Optional[FleetSensors] = None,
                 policy: Optional[AutopilotPolicy] = None,
                 actuators: Optional[Actuators] = None,
                 interlock: Optional[SafetyInterlock] = None) -> None:
        self.group = group
        self.interval = float(
            interval if interval is not None
            else config.get_flag("autopilot_interval_seconds"))
        if detector is None:
            from multiverso_tpu.shard.reshard import HotRangeDetector
            detector = HotRangeDetector(
                group.num_shards, recorder=recorder,
                window_seconds=float(
                    config.get_flag("autopilot_window_seconds")))
        self.detector = detector
        self.sensors = sensors if sensors is not None else FleetSensors(
            group, recorder=recorder, engine=engine, auditor=auditor)
        self.policy = (policy if policy is not None
                       else AutopilotPolicy(detector))
        self.actuators = (actuators if actuators is not None
                          else Actuators(group))
        self.interlock = (interlock if interlock is not None
                          else SafetyInterlock(auditor))
        self.ticks = 0
        self.last_decision: Optional[Dict[str, Any]] = None
        # every tick's verdict stays queryable even though only real
        # decisions dump — at a 5s cadence the recorder must not fill
        # with "none" records
        self.history: Deque[Dict[str, Any]] = deque(maxlen=64)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one tick ------------------------------------------------------------
    def tick_now(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full control cycle — the deterministic seam. Returns the
        tick record (decision + outcome + interlock state)."""
        self.ticks += 1
        count("AUTOPILOT_TICKS")
        now = float(now if now is not None else time.time())
        record: Dict[str, Any] = {"tick": self.ticks, "now": now}
        if not self.interlock.check():
            count("AUTOPILOT_FROZEN_SKIPS")
            record.update(action="frozen",
                          interlock=self.interlock.status())
            self.history.append(record)
            self.last_decision = record
            return record
        # a split/merge changed the topology since the last tick: the
        # detector and sensors must judge the CURRENT shard set
        self.detector.num_shards = int(self.group.num_shards)
        sense = self.sensors.read(now=now)
        decision = self.policy.decide(sense)
        record["decision"] = decision.as_dict()
        record["action"] = decision.action
        outcome: Optional[Dict[str, Any]] = None
        if decision.action != "none":
            # action-in-flight signal: other controllers (the autotuner)
            # must not step knobs while the fleet is being reshaped —
            # their objective window would measure the reshape, not the
            # knob. Cleared in the finally even when the action dies.
            gauge_set("AUTOPILOT_ACTION_INFLIGHT", 1)
            try:
                outcome = self.actuators.execute(decision)
            except AutopilotKilled as exc:
                # the chaos hook: controller death mid-action. Latch
                # frozen — a human decides whether the half-observed
                # action completed — and stop the loop.
                self.interlock.freeze(str(exc))
                flight_dump("autopilot_killed", decision=record["decision"],
                            error=str(exc),
                            policy=self.policy.state_snapshot(now))
                self._stop.set()
                outcome = {"ok": False, "action": decision.action,
                           "error": str(exc), "killed": True}
            finally:
                gauge_set("AUTOPILOT_ACTION_INFLIGHT", 0)
            self.policy.record_action(decision.action, now=now)
            record["outcome"] = outcome
            flight_dump("autopilot_decision",
                        decision=record["decision"], outcome=outcome,
                        policy=self.policy.state_snapshot(now),
                        sense=sense.as_dict())
            log.info("autopilot: %s shard=%s -> %s (%s)",
                     decision.action, decision.shard,
                     "ok" if outcome and outcome.get("ok") else "FAILED",
                     decision.reason)
        self.history.append(record)
        self.last_decision = record
        return record

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Autopilot":
        if self.interval <= 0:
            log.fatal("Autopilot.start needs autopilot_interval_seconds "
                      "> 0 (or interval=); use tick_now() for drills")
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mv-autopilot")
        self._thread.start()
        log.info("autopilot: control loop started (%d shard(s), every "
                 "%.1fs)", self.group.num_shards, self.interval)
        return self

    def _run(self) -> None:
        while not self._stop.wait(max(0.05, self.interval)):
            try:
                self.tick_now()
            except Exception as exc:  # noqa: BLE001 — the controller
                # must outlive any single bad tick
                log.error("autopilot: tick failed: %r", exc)

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)

    # -- operator surface ----------------------------------------------------
    def ack(self, operator: str = "operator") -> None:
        """Operator acknowledgement: the only way out of a freeze."""
        self.interlock.ack(operator)

    def status(self) -> Dict[str, Any]:
        return {"running": (self._thread is not None
                            and self._thread.is_alive()),
                "ticks": self.ticks,
                "interval": self.interval,
                "interlock": self.interlock.status(),
                "policy": self.policy.state_snapshot(),
                "last": self.last_decision,
                "recent": list(self.history)[-8:]}


class _AutopilotModule(types.ModuleType):
    """``mv.autopilot(group, ...)`` — the operator entrypoint — and this
    package share one name: importing the package rebinds the attribute
    on ``multiverso_tpu`` from the function to this module, so the
    module itself is callable with the same semantics."""

    def __call__(self, group: Any, interval: Optional[float] = None,
                 auditor: Any = None, **kwargs: Any) -> Autopilot:
        import multiverso_tpu as _mv
        kwargs.setdefault("engine", getattr(_mv, "_slo_engine", None))
        pilot = Autopilot(group, interval=interval, auditor=auditor,
                          **kwargs)
        if pilot.interval > 0:
            pilot.start()
        return pilot


sys.modules[__name__].__class__ = _AutopilotModule
