"""Autopilot policy: sensor snapshot in, at most ONE decision out.

The policy is deliberately boring — a priority list of guarded rules
over the :class:`~multiverso_tpu.autopilot.sensors.FleetSense` snapshot
— because a fleet controller earns trust through predictability, not
cleverness:

* **Hysteresis**: a rule's condition must hold for
  ``autopilot_hysteresis_ticks`` CONSECUTIVE ticks before it may act;
  one noisy sample never resizes the fleet. Streaks are tracked per
  action kind and reset the tick the condition breaks.
* **Cooldown**: after the autopilot executes (or fails) an action of a
  kind, that kind is barred for ``autopilot_cooldown_seconds`` — the
  fleet must be given time to show the action's effect before the
  controller reacts to its own wake.
* **Rejected alternatives ride along**: every rule that matched but was
  barred (hysteresis still building, cooldown live, ceiling/floor hit)
  is recorded on the decision, so the flight recorder answers "why did
  it NOT act" as precisely as "why did it act".

Priority order (first match wins): hot-shard split > cold-range merge >
add replica (read-tier pressure or admission shedding) >
remove replica (idle fleet) >
tier budget up (hot-tier misses) > tier budget down (over-provisioned).
Splits and merges are topology changes and therefore marked ``risky``
— the actuator rehearses them on a blue/green clone first when
``autopilot_blue_green`` is on.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from multiverso_tpu import config
from multiverso_tpu.autopilot.sensors import FleetSense

ACTIONS = ("split", "merge", "add_replica", "remove_replica",
           "tier_up", "tier_down")


@dataclass
class Decision:
    """One tick's verdict: the action (or ``none``) plus the audit trail
    the flight recorder keeps — reason, rejected alternatives, and the
    hysteresis/cooldown state that produced it."""

    action: str = "none"
    shard: Optional[int] = None
    reason: str = ""
    risky: bool = False
    params: Dict[str, Any] = field(default_factory=dict)
    alternatives: List[Dict[str, str]] = field(default_factory=list)

    def as_dict(self) -> Dict[str, Any]:
        return {"action": self.action, "shard": self.shard,
                "reason": self.reason, "risky": self.risky,
                "params": dict(self.params),
                "alternatives": list(self.alternatives)}


class AutopilotPolicy:
    """Stateful rule evaluation: streaks + cooldowns across ticks."""

    def __init__(self, detector: Any) -> None:
        self.detector = detector  # HotRangeDetector (split/merge rules)
        self._streaks: Dict[str, int] = {a: 0 for a in ACTIONS}
        self._cooldown_until: Dict[str, float] = {}
        self.hysteresis = int(
            config.get_flag("autopilot_hysteresis_ticks"))
        self.cooldown = float(
            config.get_flag("autopilot_cooldown_seconds"))
        self.max_replicas = int(config.get_flag("autopilot_max_replicas"))
        self.min_replicas = int(config.get_flag("autopilot_min_replicas"))
        self.hedge_rate = float(config.get_flag("autopilot_hedge_rate"))
        self.scaledown_qps = float(
            config.get_flag("autopilot_scaledown_qps"))
        self.tier_target = float(
            config.get_flag("autopilot_tier_target_hit_rate"))
        self.tier_step = int(config.get_flag("autopilot_tier_step_bytes"))
        self.tier_max = int(config.get_flag("autopilot_tier_max_bytes"))

    # -- cross-tick state ----------------------------------------------------
    def record_action(self, action: str,
                      now: Optional[float] = None) -> None:
        """Stamp ``action``'s cooldown and clear its streak — called for
        SUCCESSES AND FAILURES both (a failed migration must not be
        retried every tick)."""
        now = float(now if now is not None else time.time())
        self._cooldown_until[action] = now + self.cooldown
        self._streaks[action] = 0

    def state_snapshot(self, now: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Streaks + live cooldowns — rides every flight-recorder dump."""
        now = float(now if now is not None else time.time())
        return {"streaks": dict(self._streaks),
                "cooldowns": {a: round(t - now, 3)
                              for a, t in self._cooldown_until.items()
                              if t > now}}

    # -- rule plumbing -------------------------------------------------------
    def _gate(self, action: str, matched: bool, now: float,
              decision: Decision, why: str) -> bool:
        """Streak/cooldown gate: returns True when ``action`` may fire
        this tick; otherwise records the rejection on ``decision``."""
        if not matched:
            self._streaks[action] = 0
            return False
        self._streaks[action] += 1
        until = self._cooldown_until.get(action, 0.0)
        if until > now:
            decision.alternatives.append(
                {"action": action,
                 "reason": f"{why}; barred by cooldown for "
                           f"{until - now:.1f}s"})
            return False
        if self._streaks[action] < self.hysteresis:
            decision.alternatives.append(
                {"action": action,
                 "reason": f"{why}; hysteresis "
                           f"{self._streaks[action]}/{self.hysteresis}"})
            return False
        return True

    # -- the decision --------------------------------------------------------
    def decide(self, sense: FleetSense) -> Decision:
        decision = Decision()
        now = sense.now

        # 1. hot-shard split (the detector owns thresholds + proposal
        # counting; its proposal is the rule's match)
        split = self.detector.propose()
        if self._gate("split", split is not None, now, decision,
                      "hot shard" if split is None else
                      f"shard {split['shard']} at {split['rate']:.1f} "
                      f"req/s vs median {split['median']:.1f}"):
            decision.action = "split"
            decision.shard = int(split["shard"])
            decision.risky = True
            decision.params = {k: v for k, v in split.items()
                               if k != "op"}
            decision.reason = (f"shard {split['shard']} runs "
                               f"{split['rate']:.1f} req/s against a "
                               f"median of {split['median']:.1f}")
            return decision

        # 2. cold-range merge
        merge = None if split is not None else self.detector.propose_merge()
        if self._gate("merge", merge is not None, now, decision,
                      "cold adjacent shards" if merge is None else
                      f"shards {merge['shard']}+{merge['shard'] + 1} at "
                      f"{merge['rate']:.1f}/{merge['neighbor_rate']:.1f} "
                      "req/s"):
            decision.action = "merge"
            decision.shard = int(merge["shard"])
            decision.risky = True
            decision.params = {k: v for k, v in merge.items()
                               if k != "op"}
            decision.reason = (f"shards {merge['shard']} and "
                               f"{merge['shard'] + 1} both idle below "
                               f"{self.detector.cold_qps:.1f} req/s")
            return decision

        # 3. add replica: sustained read-tier pressure. High replica LAG
        # deliberately does not match — another replica tails the same
        # WAL and cures nothing; it lands as a rejected alternative so
        # the recorder shows the controller saw it and declined.
        counts = sense.replica_counts or [0]
        # Sustained admission-control shedding is the strongest overload
        # signal there is: the gate is already sacrificing training
        # writes to keep serving reads inside SLO, so capacity — not
        # tuning — is the cure. Hysteresis still applies, so one stray
        # shed event never resizes the fleet.
        shedding = sense.shed_rate > 0.0
        pressured = sense.read_pressure > self.hedge_rate or shedding
        target = (min(range(len(counts)), key=lambda k: counts[k])
                  if counts else 0)
        room = counts and counts[target] < self.max_replicas
        if pressured and not room:
            decision.alternatives.append(
                {"action": "add_replica",
                 "reason": f"read pressure {sense.read_pressure:.1f}/s "
                           f"but every shard at the "
                           f"{self.max_replicas}-replica ceiling"})
        if max(sense.replica_lag.values(), default=0) > 0 and pressured:
            decision.alternatives.append(
                {"action": "add_replica",
                 "reason": "replica lag is replay backlog, not serving "
                           "capacity — a new replica tails the same WAL"})
        why = (f"admission gate shedding {sense.shed_rate:.1f} req/s"
               if shedding else
               f"read pressure {sense.read_pressure:.1f}/s over "
               f"the {self.hedge_rate:.1f}/s threshold")
        if self._gate("add_replica", pressured and bool(room), now,
                      decision, why):
            decision.action = "add_replica"
            decision.shard = target
            decision.reason = (f"{why} sustained; shard {target} has "
                               f"the thinnest fleet ({counts[target]})")
            return decision

        # 4. remove replica: idle fleet above the floor
        removable = [k for k, c in enumerate(counts)
                     if c > self.min_replicas]
        idle = sense.total_qps < self.scaledown_qps
        if self._gate("remove_replica", idle and bool(removable), now,
                      decision,
                      f"fleet idle at {sense.total_qps:.1f} req/s"):
            fat = max(removable, key=lambda k: counts[k])
            decision.action = "remove_replica"
            decision.shard = fat
            decision.reason = (f"fleet idle at {sense.total_qps:.2f} "
                               f"req/s < {self.scaledown_qps:.2f}; "
                               f"shard {fat} keeps {counts[fat] - 1}")
            return decision

        # 5/6. tier budget rebalance from hit-rate gauges
        budget = int(config.get_flag("tier_resident_bytes"))
        hit = sense.tier_hit_rate
        grow = (hit is not None and hit < self.tier_target
                and budget + self.tier_step <= self.tier_max)
        if hit is not None and hit < self.tier_target and not grow:
            decision.alternatives.append(
                {"action": "tier_up",
                 "reason": f"hot-tier hit rate {hit:.2f} below target "
                           f"{self.tier_target:.2f} but budget at the "
                           f"{self.tier_max}-byte ceiling"})
        if self._gate("tier_up", grow, now, decision,
                      "" if hit is None else
                      f"hot-tier hit rate {hit:.2f} below "
                      f"{self.tier_target:.2f}"):
            decision.action = "tier_up"
            decision.params = {"from": budget,
                               "to": budget + self.tier_step}
            decision.reason = (f"hot-tier hit rate {hit:.2f} below "
                               f"target {self.tier_target:.2f}; growing "
                               f"resident budget to "
                               f"{budget + self.tier_step}")
            return decision

        shrink = (hit is not None and hit >= self.tier_target
                  and sense.tier_resident_bytes > 0
                  and budget - self.tier_step
                  >= 2 * sense.tier_resident_bytes)
        if self._gate("tier_down", shrink, now, decision,
                      "" if hit is None else
                      f"hit rate {hit:.2f} at target with residency "
                      f"{sense.tier_resident_bytes:.0f} under half the "
                      "budget"):
            decision.action = "tier_down"
            decision.params = {"from": budget,
                               "to": budget - self.tier_step}
            decision.reason = (f"hit rate {hit:.2f} at target while "
                               f"resident bytes "
                               f"{sense.tier_resident_bytes:.0f} use "
                               f"under half the {budget}-byte budget")
            return decision

        decision.reason = "fleet within all envelopes"
        return decision
