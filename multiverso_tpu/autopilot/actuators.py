"""Autopilot actuators: decisions become fleet operations.

Each action kind dispatches to machinery that is ALREADY crash-safe on
its own — splits and merges run the MigrationCoordinator's fencing
protocol (zero acked-Add loss by construction), replica add/remove goes
through ShardGroup's live-membership methods (manifest republished
atomically), tier rebalance writes the ``tier_resident_bytes`` flag and
resizes registered in-process stores. The actuator layer adds three
things on top:

* **Outcome truth**: every execution returns an outcome dict (ok /
  error / seconds / detail) and bumps ``AUTOPILOT_ACTIONS`` or
  ``AUTOPILOT_ACTION_FAILURES``; the control loop attaches it to the
  decision's flight-recorder record.
* **Blue/green rehearsal**: with ``autopilot_blue_green`` on, a risky
  decision (split/merge) is first executed against an ``mv.clone_fleet``
  canary bootstrapped from the live fleet; only a canary that survives
  the same migration earns the live run. The canary is always stopped.
* **`MV_AUTOPILOT_KILL` chaos**: ``before[:action]`` kills the autopilot
  before the operation starts (fleet untouched); ``mid[:action]`` kills
  it after the crash-safe operation but before any autopilot
  bookkeeping (fleet reshaped, controller dead mid-thought). Both must
  leave the fleet consistent with zero acked-Add loss — the drill in
  tests/test_autopilot.py proves it.

Tier rebalance scope: the flag write governs every table constructed
AFTER it in this process; live in-process TieredStores are resized only
when registered via ``register_tiered_store`` (shard children own their
tables and budgets — reshaping those is a restart-time decision, which
the flag write also covers for clones/restores launched from here).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from multiverso_tpu import config, log
from multiverso_tpu.autopilot.policy import Decision
from multiverso_tpu.dashboard import count


class AutopilotKilled(RuntimeError):
    """Raised by the MV_AUTOPILOT_KILL chaos hook: the control loop
    treats it as the controller dying mid-action."""


def _maybe_kill(stage: str, action: str) -> None:
    spec = os.environ.get("MV_AUTOPILOT_KILL", "")
    if not spec:
        return
    want_stage, _, want_action = spec.partition(":")
    if want_stage != stage:
        return
    if want_action and want_action != action:
        return
    raise AutopilotKilled(f"MV_AUTOPILOT_KILL={spec} fired at stage "
                          f"{stage!r} of action {action!r}")


class Actuators:
    """Executes :class:`Decision` values against a live ShardGroup."""

    def __init__(self, group: Any, coordinator: Any = None) -> None:
        self.group = group
        self._coordinator = coordinator
        self._tiered_stores: List[Any] = []

    @property
    def coordinator(self):
        if self._coordinator is None:
            from multiverso_tpu.shard.reshard import MigrationCoordinator
            self._coordinator = MigrationCoordinator(self.group)
        return self._coordinator

    def register_tiered_store(self, store: Any) -> None:
        """Opt an in-process TieredStore into live budget rebalance."""
        self._tiered_stores.append(store)

    # -- execution -----------------------------------------------------------
    def execute(self, decision: Decision) -> Dict[str, Any]:
        """Run ``decision``; returns the outcome record. Raises
        :class:`AutopilotKilled` only for the chaos hook — real
        execution failures come back as ``ok=False`` outcomes."""
        action = decision.action
        t0 = time.monotonic()
        _maybe_kill("before", action)
        try:
            if decision.risky and \
                    bool(config.get_flag("autopilot_blue_green")):
                self._rehearse(decision)
            detail = self._dispatch(decision)
        except AutopilotKilled:
            raise
        except Exception as exc:  # noqa: BLE001 — one failed action
            # must not kill the control loop; the outcome records it
            count("AUTOPILOT_ACTION_FAILURES")
            log.error("autopilot: %s failed: %r", action, exc)
            return {"ok": False, "action": action,
                    "error": f"{type(exc).__name__}: {exc}",
                    "seconds": time.monotonic() - t0}
        # the underlying operation committed; a kill here is the
        # controller dying mid-thought AFTER the crash-safe part
        _maybe_kill("mid", action)
        count("AUTOPILOT_ACTIONS")
        return {"ok": True, "action": action, "detail": detail,
                "seconds": time.monotonic() - t0}

    def _dispatch(self, decision: Decision) -> Any:
        action = decision.action
        if action == "split":
            self.coordinator.split(int(decision.shard))
            return {"shard": decision.shard,
                    "num_shards": self.group.num_shards}
        if action == "merge":
            self.coordinator.merge(int(decision.shard))
            return {"shard": decision.shard,
                    "num_shards": self.group.num_shards}
        if action == "add_replica":
            endpoint = self.group.add_replica(int(decision.shard))
            return {"shard": decision.shard, "endpoint": endpoint}
        if action == "remove_replica":
            endpoint = self.group.remove_replica(int(decision.shard))
            return {"shard": decision.shard, "endpoint": endpoint}
        if action in ("tier_up", "tier_down"):
            return self._retier(int(decision.params["to"]))
        raise ValueError(f"autopilot: unknown action {action!r}")

    def _retier(self, new_budget: int) -> Dict[str, Any]:
        config.set_flag("tier_resident_bytes", int(new_budget))
        resized = 0
        for store in self._tiered_stores:
            store.budget = int(new_budget)
            store._promote_slack = max(store.row_bytes * 64,
                                       store.budget // 8)
            store.maintain()  # shrink demotes immediately, grow is a no-op
            resized += 1
        return {"budget": int(new_budget), "stores_resized": resized}

    def _rehearse(self, decision: Decision) -> None:
        """Blue/green: run the same migration on a clone_fleet canary
        bootstrapped from the live group; a canary that dies vetoes the
        live run (the raised error becomes the action's outcome)."""
        from multiverso_tpu import clone_fleet
        from multiverso_tpu.shard.reshard import MigrationCoordinator
        log.info("autopilot: rehearsing %s of shard %s on a blue/green "
                 "canary", decision.action, decision.shard)
        canary = clone_fleet(self.group)
        try:
            coord = MigrationCoordinator(canary)
            if decision.action == "split":
                coord.split(int(decision.shard))
            else:
                coord.merge(int(decision.shard))
        finally:
            canary.stop()
