"""Fleet sensors: one consistent telemetry snapshot per control tick.

The autopilot never acts on raw counters — every tick starts by freezing
the state of the telemetry plane into a :class:`FleetSense` value:
per-shard request rates out of the ``ROUTER_SHARD<k>_SECONDS`` ring
(the same series the hot-range detector reads), read-tier pressure
(hedges + replica refusals + primary fallbacks per second), replica
replay lag probed over the slot-free watermark RPC, tiered-store hit
rates and resident bytes, the client Get p99, and the queryable state
of the SLO burn engine and the fleet auditor. The policy then decides
over the snapshot, so a decision and its flight-recorder record always
describe the SAME instant.

Replica lag is probed, not scraped: ``REPLICA_LAG_RECORDS`` is set by
the replica CHILD process's gauge registry and is invisible to the
launcher's recorder, so the sensors fan one ``mv.watermark`` probe per
replica endpoint and republish the worst lag per shard as the local
``FLEET_SHARD<k>_REPLICA_LAG`` gauge — which also gives operators (and
Prometheus, via the shard-labelled exposition) a per-shard pressure
series in the controlling process.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from multiverso_tpu import config
from multiverso_tpu.dashboard import gauge_set


@dataclass
class FleetSense:
    """The telemetry plane at one instant, as the policy consumes it."""

    now: float
    shard_rates: List[float] = field(default_factory=list)
    total_qps: float = 0.0
    read_pressure: float = 0.0      # hedges+refusals+fallbacks per sec
    shed_rate: float = 0.0          # SHED_ADDS+SHED_GETS per sec
    tenant_shed_rates: Dict[str, float] = field(default_factory=dict)
    replica_lag: Dict[int, int] = field(default_factory=dict)
    replica_counts: List[int] = field(default_factory=list)
    get_p99: float = 0.0
    tier_hit_rate: Optional[float] = None   # None: no tiered traffic
    tier_resident_bytes: float = 0.0
    slo_firing: List[str] = field(default_factory=list)
    audit_divergent: bool = False

    def as_dict(self) -> Dict[str, Any]:
        return {"now": self.now, "shard_rates": list(self.shard_rates),
                "total_qps": self.total_qps,
                "read_pressure": self.read_pressure,
                "shed_rate": self.shed_rate,
                "tenant_shed_rates": dict(self.tenant_shed_rates),
                "replica_lag": dict(self.replica_lag),
                "replica_counts": list(self.replica_counts),
                "get_p99": self.get_p99,
                "tier_hit_rate": self.tier_hit_rate,
                "tier_resident_bytes": self.tier_resident_bytes,
                "slo_firing": list(self.slo_firing),
                "audit_divergent": self.audit_divergent}


class FleetSensors:
    """Reads the recorder/engine/auditor into :class:`FleetSense` values.

    ``group`` is the ShardGroup under control (shard count and replica
    endpoints come from its live manifest), ``recorder`` a
    TimeSeriesRecorder (default: the global one), ``engine``/``auditor``
    the queryable SLO and audit planes (either may be None — the
    corresponding fields degrade to empty/False), ``probe`` the
    watermark RPC seam tests inject."""

    def __init__(self, group: Any, recorder: Any = None,
                 engine: Any = None, auditor: Any = None,
                 window: Optional[float] = None,
                 probe: Any = None,
                 probe_timeout: float = 2.0) -> None:
        if recorder is None:
            from multiverso_tpu.obs.timeseries import TIMESERIES
            recorder = TIMESERIES
        self.group = group
        self.recorder = recorder
        self.engine = engine
        self.auditor = auditor
        self.window = float(window if window is not None else
                            config.get_flag("autopilot_window_seconds"))
        if probe is None:
            from multiverso_tpu.runtime.remote import fetch_watermark
            probe = fetch_watermark
        self._probe = probe
        self._probe_timeout = float(probe_timeout)

    # -- pieces --------------------------------------------------------------
    def shard_rates(self) -> List[float]:
        rates: List[float] = []
        for k in range(int(self.group.num_shards)):
            hist = self.recorder.window_histogram(
                f"ROUTER_SHARD{k}_SECONDS", self.window)
            n = int(hist.count) if hist is not None else 0
            rates.append(n / self.window)
        return rates

    def read_pressure(self) -> float:
        return sum(self.recorder.rate(name, self.window)
                   for name in ("READ_HEDGES",
                                "READ_REPLICA_REFUSALS_SEEN",
                                "READ_PRIMARY_FALLBACKS"))

    def shed_rate(self) -> float:
        """Admission-control refusals per second (both lanes): a sustained
        non-zero rate means the fleet is in brownout — the overload gate
        (docs/fault_tolerance.md) is actively trading training writes for
        serving-read latency, and adding replicas or shards is the fix."""
        return sum(self.recorder.rate(name, self.window)
                   for name in ("SHED_ADDS", "SHED_GETS"))

    def tenant_shed_rates(self) -> Dict[str, float]:
        """Per-tenant shed rate (``TENANT_<t>_SHED`` per second): the
        disaggregation of :meth:`shed_rate` that stops one noisy tenant
        masquerading as fleet-wide capacity pressure — a policy can see
        that the shedding is confined to the tenant whose quota is doing
        its job. Degrades to {} on recorders without the tenant view
        (tests inject minimal fakes)."""
        rates = getattr(self.recorder, "tenant_rates", None)
        if rates is None:
            return {}
        return dict(rates("SHED", self.window))

    def replica_lag(self) -> Dict[int, int]:
        """Worst replay lag (records) per shard, probed concurrently
        over the slot-free watermark RPC; unreachable replicas are
        skipped (the auditor owns unreachability)."""
        fleets = list(getattr(self.group, "replica_endpoints", []) or [])
        lags: Dict[int, int] = {}
        lock = threading.Lock()

        def probe(shard: int, ep: str) -> None:
            try:
                wm = self._probe(ep, timeout=self._probe_timeout)
            except (OSError, RuntimeError):
                return
            lag = int(wm.get("lag", 0) or 0)
            with lock:
                lags[shard] = max(lags.get(shard, 0), lag)

        threads = [threading.Thread(target=probe, args=(k, ep),
                                    daemon=True, name="mv-autopilot-probe")
                   for k, fleet in enumerate(fleets) for ep in fleet]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self._probe_timeout + 1.0)
        for shard, lag in lags.items():
            # republish locally: the per-shard pressure series operators
            # scrape from the CONTROLLING process (docs/observability.md)
            gauge_set(f"FLEET_SHARD{shard}_REPLICA_LAG", lag)
        return lags

    def tier_hit_rate(self) -> Optional[float]:
        hot = self.recorder.rate("TIER_HOT_HITS", self.window)
        cold = self.recorder.rate("TIER_COLD_HITS", self.window)
        if hot + cold <= 0:
            return None
        return hot / (hot + cold)

    # -- the snapshot --------------------------------------------------------
    def read(self, now: Optional[float] = None) -> FleetSense:
        rates = self.shard_rates()
        fleets = list(getattr(self.group, "replica_endpoints", []) or [])
        counts = [len(fleets[k]) if k < len(fleets) else 0
                  for k in range(int(self.group.num_shards))]
        firing: List[str] = []
        if self.engine is not None:
            firing = list(self.engine.firing())
        divergent = bool(self.auditor is not None
                         and getattr(self.auditor, "divergent", False))
        return FleetSense(
            now=float(now if now is not None else time.time()),
            shard_rates=rates,
            total_qps=sum(rates),
            read_pressure=self.read_pressure(),
            shed_rate=self.shed_rate(),
            tenant_shed_rates=self.tenant_shed_rates(),
            replica_lag=self.replica_lag(),
            replica_counts=counts,
            get_p99=self.recorder.quantile("CLIENT_REQUEST_SECONDS",
                                           0.99, self.window),
            tier_hit_rate=self.tier_hit_rate(),
            tier_resident_bytes=self.recorder.gauge("TIER_RESIDENT_BYTES"),
            slo_firing=firing,
            audit_divergent=divergent)
