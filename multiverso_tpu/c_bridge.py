"""Python side of the C API bridge (reference: ``src/c_api.cpp`` marshalling).

Called by the embedded interpreter inside ``libmultiverso_tpu.so``. The C
shim passes raw host pointers wrapped as memoryviews; this module views them
as numpy arrays (zero-copy) and drives the real table API. Handles are small
ints so they pack into the reference's ``void*`` TableHandler.
"""

from __future__ import annotations

import threading
from typing import Dict, List

import numpy as np

import multiverso_tpu as mv

_tables: Dict[int, object] = {}
_next_handle = [1]
_lock = threading.Lock()


def _register(table) -> int:
    with _lock:
        handle = _next_handle[0]
        _next_handle[0] += 1
        _tables[handle] = table
        return handle


def _f32(view, size) -> np.ndarray:
    return np.frombuffer(view, dtype=np.float32, count=size)


def _i32(view, count) -> np.ndarray:
    return np.frombuffer(view, dtype=np.int32, count=count)


# -- lifecycle ---------------------------------------------------------------

def init(argv: List[str]) -> None:
    mv.init(argv)


def shutdown() -> None:
    with _lock:
        _tables.clear()
    mv.shutdown()


def barrier() -> None:
    mv.barrier()


def num_workers() -> int:
    return mv.num_workers()


def num_servers() -> int:
    return mv.num_servers()


def worker_id() -> int:
    return mv.worker_id()


def server_id() -> int:
    return mv.server_id()


def rank() -> int:
    return mv.rank()


def size() -> int:
    return mv.size()


def set_flag(name: str, value: str) -> None:
    mv.set_flag(name, value)


# -- array table -------------------------------------------------------------

def new_array_table(size: int) -> int:
    return _register(mv.create_table("array", size, np.float32))


def array_get(handle: int, view, size: int) -> None:
    out = _f32(view, size)
    np.copyto(out, _tables[handle].get())


def array_add(handle: int, view, size: int, async_: int) -> None:
    delta = _f32(view, size).copy()
    table = _tables[handle]
    if async_:
        table.add_async(delta)
    else:
        table.add(delta)


# -- matrix table ------------------------------------------------------------

def new_matrix_table(num_row: int, num_col: int) -> int:
    return _register(mv.create_table("matrix", num_row, num_col, np.float32))


def matrix_get_all(handle: int, view, size: int) -> None:
    out = _f32(view, size)
    np.copyto(out, _tables[handle].get().reshape(-1))


def matrix_add_all(handle: int, view, size: int, async_: int) -> None:
    table = _tables[handle]
    delta = _f32(view, size).copy().reshape(table.num_row, table.num_col)
    if async_:
        table.add_async(delta)
    else:
        table.add(delta)


def matrix_get_rows(handle: int, view, size: int, ids_view, n: int) -> None:
    table = _tables[handle]
    ids = _i32(ids_view, n)
    out = _f32(view, size)
    np.copyto(out, table.get(ids).reshape(-1))


def matrix_add_rows(handle: int, view, size: int, ids_view, n: int,
                    async_: int) -> None:
    table = _tables[handle]
    ids = _i32(ids_view, n).copy()
    delta = _f32(view, size).copy().reshape(n, table.num_col)
    if async_:
        table.add_async(delta, row_ids=ids)
    else:
        table.add(delta, row_ids=ids)
