"""Checkpoint / resume: table Store/Load over the Stream layer + a periodic
driver.

Reference capability (not copied): every ``ServerTable`` is ``Serializable``
with ``Store(Stream*)/Load(Stream*)`` over the URI/Stream IO layer
(``include/multiverso/table_interface.h:61-75``), but nothing in the snapshot
drove them on a schedule — the Dockerfile's lost ``checkpoint``/``restore``
test targets show it was a supported workflow. The rebuild ships the hooks
AND an actual driver.

Format: a tiny self-describing binary header (dtype, ndim, dims) per array —
stable across hosts, independent of pickle. ``CheckpointDriver`` snapshots
every N seconds or every N steps to ``<uri>/table_<id>.mvckpt``.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import List, Optional

import numpy as np

from multiverso_tpu import io as mv_io
from multiverso_tpu import log
from multiverso_tpu.dashboard import observe

_MAGIC = b"MVTC"


def write_array(stream: mv_io.Stream, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode("ascii")
    stream.write(_MAGIC)
    stream.write(struct.pack("<B", len(dt)))
    stream.write(dt)
    stream.write(struct.pack("<B", arr.ndim))
    stream.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
    stream.write(arr.tobytes())


_STATE_MAGIC = b"MVS2"


def write_state_dict(stream: mv_io.Stream, states) -> None:
    """Updater-state trailer (v2 checkpoints): name-keyed arrays after the
    data frame. The reference's Store hook serialized only table data
    (table_interface.h:61-75) — optimizer state silently reset on
    restore; here AdaGrad/momentum/DCASGD accumulators survive, which the
    resume-exactness test requires."""
    stream.write(_STATE_MAGIC)
    names = sorted(states)
    stream.write(struct.pack("<i", len(names)))
    for name in names:
        nb = name.encode("utf-8")
        stream.write(struct.pack("<B", len(nb)))
        stream.write(nb)
        write_array(stream, states[name])


def read_state_dict(stream: mv_io.Stream) -> dict:
    """Read the v2 trailer; {} for v1 checkpoints (data-only) so restores
    of old snapshots still work — their updater state resets, as it
    always did."""
    magic = stream.read(4)
    if magic != _STATE_MAGIC:
        return {}
    (count,) = struct.unpack("<i", stream.read(4))
    states = {}
    for _ in range(count):
        (nlen,) = struct.unpack("<B", stream.read(1))
        name = stream.read(nlen).decode("utf-8")
        states[name] = read_array(stream)
    return states


def read_array(stream: mv_io.Stream) -> np.ndarray:
    magic = stream.read(4)
    if magic != _MAGIC:
        log.fatal("checkpoint: bad magic %r", magic)
    (dtlen,) = struct.unpack("<B", stream.read(1))
    dtype = np.dtype(stream.read(dtlen).decode("ascii"))
    (ndim,) = struct.unpack("<B", stream.read(1))
    shape = struct.unpack(f"<{ndim}q", stream.read(8 * ndim))
    count = int(np.prod(shape)) if ndim else 1
    data = stream.read(count * dtype.itemsize)
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


def _require_leader(verb: str) -> None:
    """Multihost: snapshot/restore drive from the leader only — a follower
    calling a raw table's store/load would run the device->host collective
    OUTSIDE the lockstep replay stream and desynchronize the mesh. The
    leader's lockstep wrapper broadcasts the collective to followers."""
    from multiverso_tpu.runtime.zoo import Zoo
    zoo = Zoo.instance()
    if zoo.multihost is not None and zoo.rank != 0:
        log.fatal("checkpoint %s must run on the multihost leader (rank 0);"
                  " this is rank %d — followers participate via lockstep "
                  "replay automatically", verb, zoo.rank)


def _run_serialized(fn):
    """Execute ``fn`` on the dispatcher thread, serialized with table
    traffic. Snapshot/restore MUST order against in-flight adds: async
    and deferred-apply (deterministic) adds complete to the caller before
    the device update runs, so a direct app-thread store could capture a
    mid-application table (caught by the resume-exactness test). Falls
    back to inline execution when no dispatcher exists (ma mode)."""
    from multiverso_tpu.runtime.zoo import Zoo

    server = Zoo.instance().server
    if server is None or not hasattr(server, "run_serialized"):
        return fn()
    # unbounded: a timeout would close the caller's stream while the
    # dispatcher is mid-write, leaving a truncated snapshot behind
    return server.run_serialized(fn, timeout=None)


def store_table(table, address: str) -> None:
    """Store one table (worker or server handle) to a URI. Atomic: the
    bytes land in a temp sibling and commit with a rename, so a crash
    mid-write never leaves a truncated snapshot at the final name (which
    ``restore_tables`` would hit as a fatal bad-magic error, defeating
    restart recovery)."""
    _require_leader("snapshot")
    t0 = time.perf_counter()
    server = getattr(table, "_server_table", table)
    fs = mv_io.fs_for(address)
    tmp = f"{address}.tmp-{os.getpid()}"
    with mv_io.get_stream(tmp, "w") as stream:
        _run_serialized(lambda: server.store(stream))
    fs.replace(tmp, address)
    # per-table store cost (device->host read + stream write + rename):
    # the tail of this distribution is how long snapshots stall applies
    observe("CHECKPOINT_STORE_SECONDS", time.perf_counter() - t0)


def load_table(table, address: str) -> None:
    _require_leader("restore")
    t0 = time.perf_counter()
    server = getattr(table, "_server_table", table)
    with mv_io.get_stream(address, "r") as stream:
        _run_serialized(lambda: server.load(stream))
    observe("CHECKPOINT_RESTORE_SECONDS", time.perf_counter() - t0)


def restore_tables(tables: List, directory: str) -> int:
    """Load the latest ``CheckpointDriver`` snapshot for each table found
    under ``directory``; returns how many tables were restored. The
    server-restart recovery hook (docs/fault_tolerance.md): a restarted
    serving process re-creates its tables (same order, so table ids match
    the snapshot's) and calls this BEFORE ``serve()``, so clients that
    reconnect-and-resume read restored state rather than fresh zeros."""
    fs = mv_io.fs_for(directory)
    restored = 0
    for table in tables:
        server = getattr(table, "_server_table", table)
        tid = getattr(server, "table_id", 0)
        path = mv_io.join(directory, f"table_{tid}.mvckpt")
        if fs.exists(path):
            load_table(table, path)
            restored += 1
    return restored


class CheckpointDriver:
    """Periodic snapshot driver over a set of tables.

    ``interval_steps``: snapshot on every Nth ``step()`` call;
    ``interval_seconds``: or on a wall-clock timer thread. Snapshots are
    written to ``<directory>/table_<id>.mvckpt`` with an atomic rename.
    ``directory`` is a URI: any registered scheme works (``file://`` local,
    ``mvfs://host:port/run`` remote — the reference checkpointed through its
    Stream layer to local or HDFS storage the same way, io.cpp:8-23).

    ``wal``: a :class:`multiverso_tpu.durable.wal.WalWriter`
    (``mv.wal_writer()`` on a serving process) switches snapshots to the
    durable protocol — one dispatcher-serialized block that rotates the
    log, stores every table into a fresh ``gen_<g>/`` directory, commits
    the MANIFEST, and retires segments/generations older than the
    snapshot. Restart recovery for that layout is ``mv.durable_recover``
    (snapshot + WAL replay), not :meth:`restore`.
    """

    def __init__(self, tables: List, directory: str,
                 interval_steps: Optional[int] = None,
                 interval_seconds: Optional[float] = None,
                 wal=None) -> None:
        self.tables = list(tables)
        self.directory = directory
        self.wal = wal
        if wal is not None and wal.directory != directory:
            # one root holds MANIFEST + gen_<g>/ + wal/ — recovery reads
            # them as a unit, so a split layout could never be replayed
            log.fatal("CheckpointDriver: directory %r must equal the WAL "
                      "root %r (MANIFEST, snapshots and segments are one "
                      "recovery unit)", directory, wal.directory)
        self.interval_steps = interval_steps
        self.interval_seconds = interval_seconds
        self._fs = mv_io.fs_for(directory)
        self._step = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fs.makedirs(directory)
        if interval_seconds:
            self._thread = threading.Thread(target=self._timer_loop, daemon=True)
            self._thread.start()

    def _timer_loop(self) -> None:
        while not self._stop.wait(self.interval_seconds):
            try:
                self.snapshot()
            except Exception as exc:  # remote store down ≠ kill the timer
                log.error("checkpoint: periodic snapshot to %s failed (%r); "
                          "will retry next interval", self.directory, exc)

    def step(self) -> None:
        self._step += 1
        if self.interval_steps and self._step % self.interval_steps == 0:
            self.snapshot()

    def snapshot(self) -> None:
        with self._lock:
            if self.wal is not None:
                self._durable_snapshot()
                return
            for table in self.tables:
                server = getattr(table, "_server_table", table)
                tid = getattr(server, "table_id", 0)
                store_table(table, mv_io.join(self.directory,
                                              f"table_{tid}.mvckpt"))
            log.debug("checkpoint: snapshot of %d tables -> %s",
                      len(self.tables), self.directory)

    def _durable_snapshot(self) -> None:
        """Snapshot + log compaction as ONE dispatcher-serialized block:
        no add can land between the rotation and the stores, so segments
        >= the rotation point contain exactly the post-snapshot adds.
        The MANIFEST commit is the atomic switch; a crash anywhere in
        here leaves the previous (generation, first_segment) pair live
        and fully replayable."""
        def run():
            first_segment = self.wal.rotate()
            generation = self.wal.generation + 1
            gen_dir = mv_io.join(self.directory, f"gen_{generation}")
            self._fs.makedirs(gen_dir)
            for table in self.tables:
                server = getattr(table, "_server_table", table)
                tid = getattr(server, "table_id", 0)
                store_table(table, mv_io.join(gen_dir,
                                              f"table_{tid}.mvckpt"))
            self.wal.commit_snapshot(generation, first_segment)
        _run_serialized(run)
        log.debug("checkpoint: durable snapshot of %d tables -> %s "
                  "(generation %d)", len(self.tables), self.directory,
                  self.wal.generation)

    def restore(self) -> bool:
        """Load the latest snapshot; returns False when none exists."""
        with self._lock:
            return restore_tables(self.tables, self.directory) > 0

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
