"""Client-side read tier: bounded-staleness cache + replica/hedged routing.

The read half of the serving story (docs/serving.md). Heavy-user-traffic
serving is read-dominated, yet every Get used to burn a worker slot on
the one primary per shard. This module lets a
:class:`~multiverso_tpu.runtime.remote.RemoteClient` route Gets through
three layers, cheapest first:

1. :class:`ReadCache` — a byte-bounded LRU keyed by (table, ids). A hit
   never touches the wire. Entries carry the watermark they were served
   at; they invalidate the instant the client observes a primary append
   watermark more than the staleness budget ahead (watermark
   invalidation), and expire after ``read_lease_seconds`` of wall clock
   regardless (the lease bounds the blind window during which the client
   hears nothing from the serving tier).
2. :class:`ReplicaReader` — slot-free ``Request_Read`` frames to a
   serving read replica (durable/standby.py). The replica admission-
   checks the request's staleness budget against its replay lag and
   stamps the reply with its replay watermark.
3. The primary — the pre-replica path, used when the preference is
   ``primary``, when no replica is fresh enough, or as the transparent
   fallback when replicas refuse, die, or time out. Fallback is silent:
   a caller never sees a replica failure, only (at worst) primary
   latency.

``hedged`` preference (the tail-tolerance policy): fire the first-choice
replica, arm a timer at the p95 of recent read latencies, and fire the
second choice when it expires with no reply. First reply wins; the loser
is cancelled (its late reply is dropped on the floor, its in-flight
entry reaped).

Consistency contract, spelled out: a Get answered through this tier is
at most ``read_staleness_records`` WAL records staler than the primary's
append watermark as observed by the serving replica (the generalized
SSP bound, Ho et al. NIPS'13) — plus, for cache hits only, at most
``read_lease_seconds`` of wall clock during which the client heard
nothing newer. Callers that need the primary's exact present read with
``read_preference=primary`` (the default — this whole tier is opt-in).

``Request_Query`` (server-side top-k retrieval pushdown, query/) rides
the same three layers under the same budgets: a namespaced cache key
(query bytes + k + metric), replica admission against the staleness
budget, p95-derived hedging, silent primary fallback. A separate
``QUERY_*`` counter family keeps retrieval traffic legible apart from
training Gets.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import count, gauge_add, gauge_set
from multiverso_tpu.fault.inject import make_net
from multiverso_tpu.obs.trace import hop, tag_tenant
from multiverso_tpu.runtime.admission import resolve_tenant
from multiverso_tpu.runtime import wire
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id

READ_PREFERENCES = ("primary", "replica", "hedged")


def validate_read_preference(value: str) -> str:
    value = str(value).strip().lower()
    if value not in READ_PREFERENCES:
        log.fatal("read_preference must be one of %s, got %r",
                  "|".join(READ_PREFERENCES), value)
    return value


# -- cache keying -------------------------------------------------------------

def _key_part(x: Any) -> Any:
    from multiverso_tpu.updaters import GetOption
    if x is None or isinstance(x, (int, float, str, bytes, bool)):
        return x
    if isinstance(x, np.ndarray):
        # exact bytes, not a hash: a digest collision would silently
        # serve the wrong rows. Hot-key id arrays are small.
        return (x.dtype.str, x.shape, x.tobytes())
    if isinstance(x, (list, tuple)):
        return tuple(_key_part(e) for e in x)
    if isinstance(x, GetOption):
        # worker identity does not shape a plain Get's result; keying it
        # out lets one client's threads share entries
        return "GetOption"
    raise TypeError(f"uncacheable request part {type(x)!r}")


def cache_key(table_id: int, request: Any) -> Optional[Tuple]:
    """Hashable cache key for a Get request, or None when the request
    shape is not cacheable (unknown envelope types)."""
    try:
        return (int(table_id), _key_part(request))
    except TypeError:
        return None


def _result_nbytes(value: Any) -> int:
    if isinstance(value, np.ndarray):
        return int(value.nbytes) + 64
    if isinstance(value, (list, tuple)):
        return 64 + sum(_result_nbytes(v) for v in value)
    if isinstance(value, dict):
        return 64 + sum(_result_nbytes(v) for v in value.values())
    return 64


def _copy_result(value: Any) -> Any:
    """Defensive copy both ways (store and serve): cached arrays must not
    alias buffers the caller may mutate."""
    if isinstance(value, np.ndarray):
        return value.copy()
    if isinstance(value, tuple):
        return tuple(_copy_result(v) for v in value)
    if isinstance(value, list):
        return [_copy_result(v) for v in value]
    if isinstance(value, dict):
        return {k: _copy_result(v) for k, v in value.items()}
    return value


class _CacheEntry:
    __slots__ = ("value", "watermark", "stamp", "nbytes")

    def __init__(self, value: Any, watermark: int, stamp: float,
                 nbytes: int) -> None:
        self.value = value
        self.watermark = watermark
        self.stamp = stamp
        self.nbytes = nbytes


class ReadCache:
    """Bounded-staleness client read cache: LRU by (table, ids), byte-
    capped, lease + watermark invalidation (module docstring for the
    contract)."""

    def __init__(self, capacity_bytes: int,
                 lease_seconds: Optional[float] = None) -> None:
        self.capacity = int(capacity_bytes)
        self.lease = float(lease_seconds if lease_seconds is not None
                           else config.get_flag("read_lease_seconds"))
        self._lru: "OrderedDict[Tuple, _CacheEntry]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        # newest PRIMARY append watermark observed (any reply from the
        # primary carries it); the horizon entries age against
        self.horizon = -1

    # -- watermark horizon ---------------------------------------------------
    def observe_primary(self, watermark: int) -> None:
        """A reply from the PRIMARY advertised its append watermark. A
        REGRESSION means a different primary incarnation (failover /
        restart — sequences restart at 0): nothing cached is comparable,
        flush everything."""
        if watermark < 0:
            return
        with self._lock:
            if watermark < self.horizon:
                self._lru.clear()
                self._bytes = 0
                count("READ_CACHE_EPOCH_FLUSHES")
            self.horizon = watermark
        gauge_set("READ_CACHE_BYTES", self._bytes)

    def observe_replica(self, watermark: int) -> None:
        """A replica reply's replay watermark: a lower bound on the
        primary's append watermark — advance-only (a lagging replica must
        not look like a failover)."""
        if watermark < 0:
            return
        with self._lock:
            if watermark > self.horizon:
                self.horizon = watermark

    # -- lookup / store ------------------------------------------------------
    def lookup(self, key: Tuple, budget: int) -> Optional[Any]:
        now = time.monotonic()
        with self._lock:
            entry = self._lru.get(key)
            if entry is None:
                return None
            stale = (now - entry.stamp > self.lease
                     or (budget >= 0 and entry.watermark >= 0
                         and self.horizon >= 0
                         and self.horizon - entry.watermark > budget))
            if stale:
                del self._lru[key]
                self._bytes -= entry.nbytes
                return None
            self._lru.move_to_end(key)
            return _copy_result(entry.value)

    def store(self, key: Tuple, value: Any, watermark: int) -> None:
        nbytes = _result_nbytes(value)
        if nbytes > self.capacity:
            return  # a single whale must not evict the whole working set
        value = _copy_result(value)
        now = time.monotonic()
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._lru[key] = _CacheEntry(value, watermark, now, nbytes)
            self._bytes += nbytes
            while self._bytes > self.capacity and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._bytes -= evicted.nbytes
        gauge_set("READ_CACHE_BYTES", self._bytes)

    def resize(self, capacity_bytes: int) -> None:
        """Live capacity change (flag watch seam): shrinking evicts the
        LRU tail immediately, growing just raises the bar."""
        with self._lock:
            self.capacity = int(capacity_bytes)
            while self._bytes > self.capacity and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._bytes -= evicted.nbytes
        gauge_set("READ_CACHE_BYTES", self._bytes)

    def invalidate_table(self, table_id: int) -> None:
        """Write-through invalidation: this client wrote to the table, so
        its own cached reads of it are suspect (read-your-writes at cache
        granularity)."""
        with self._lock:
            doomed = [k for k in self._lru if k[0] == int(table_id)]
            for k in doomed:
                self._bytes -= self._lru.pop(k).nbytes
        gauge_set("READ_CACHE_BYTES", self._bytes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)


# -- replica reader -----------------------------------------------------------

class _Refused(RuntimeError):
    """The replica declined the read (stale / unsynced / lost primary) —
    a routing signal, never surfaced to the caller."""


class _PendingRead:
    __slots__ = ("cb", "t0")

    def __init__(self, cb: Callable, t0: float) -> None:
        self.cb = cb
        self.t0 = t0


class ReplicaReader:
    """One replica read connection: slot-free ``Request_Read`` frames
    correlated by msg_id. No worker slot, no lease, no retransmission —
    failures report to the router, which owns failover (next replica,
    then primary). Keeps a small latency ring for the hedged policy's
    p95-derived delay, and availability state (dead/stale backoff) for
    the router's round-robin."""

    DEAD_BACKOFF = 0.5    # redial a dead replica at most this often
    STALE_BACKOFF = 0.2   # skip a just-refused replica this long

    def __init__(self, endpoint: str) -> None:
        self.endpoint = endpoint
        self._net = None
        self._net_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, _PendingRead] = {}
        self.latencies: deque = deque(maxlen=128)
        self.dead_until = 0.0
        self.stale_until = 0.0
        self._compress = bool(config.get_flag("wire_compression"))
        # deadline budget stamped on each Request_Read (0 = none): a
        # replica drowning in reads drops the expired ones at drain
        # instead of serving answers nobody is waiting for
        self._deadline_budget = float(
            config.get_flag("request_deadline_seconds"))
        self._closed = False

    def available(self, now: float) -> bool:
        return not self._closed and now >= max(self.dead_until,
                                               self.stale_until)

    # -- lifecycle -----------------------------------------------------------
    def _ensure_net(self):
        with self._net_lock:
            if self._net is None:
                if self._closed:
                    raise OSError("reader closed")
                net = make_net()
                net.rank = -1
                net.connect([self.endpoint])
                self._net = net
                threading.Thread(target=self._pump, args=(net,),
                                 daemon=True,
                                 name="mv-replica-read-pump").start()
            return self._net

    def close(self) -> None:
        self._closed = True
        with self._net_lock:
            net, self._net = self._net, None
        if net is not None:
            net.finalize()
        self._fail_all(ConnectionError("reader closed"))

    # -- read path -----------------------------------------------------------
    def read_async(self, table_id: int, request: Any, budget: int,
                   cb: Callable, req_id: int = 0,
                   trace: bool = False, query: bool = False
                   ) -> Optional[int]:
        """Fire one read; ``cb(result, watermark, error)`` exactly once
        unless the token is cancelled first. Returns the cancellation
        token (msg_id), or None when the send itself failed (the reader
        marks itself dead; the router moves on). ``req_id``/``trace``
        thread the caller's span through the slot-free frame so the
        replica's hops land under the same trace id. ``query`` sends a
        ``Request_Query`` (top-k pushdown) instead — same slot-free
        frame shape, same admission, Reply_Query correlated identically."""
        msg_id = next_msg_id()
        with self._lock:
            self._pending[msg_id] = _PendingRead(cb, time.monotonic())
        msg = Message(src=-1, dst=0,
                      type=(MsgType.Request_Query if query
                            else MsgType.Request_Read),
                      table_id=table_id, msg_id=msg_id,
                      req_id=int(req_id), trace=bool(trace),
                      watermark=int(budget),
                      deadline=(time.monotonic() + self._deadline_budget
                                if self._deadline_budget > 0 else 0.0),
                      data=wire.encode(request, compress=self._compress))
        try:
            self._ensure_net().send(msg)
        except OSError:
            with self._lock:
                self._pending.pop(msg_id, None)
            self._mark_dead()
            return None
        return msg_id

    def cancel(self, token: int) -> None:
        """Loser-cancel: the late reply (if it ever lands) is dropped."""
        with self._lock:
            self._pending.pop(token, None)

    def _mark_dead(self) -> None:
        self.dead_until = time.monotonic() + self.DEAD_BACKOFF
        with self._net_lock:
            net, self._net = self._net, None
        if net is not None:
            net.finalize()

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for pend in pending:
            pend.cb(None, -1, exc)

    def _pump(self, net) -> None:
        while True:
            try:
                msg = net.recv()
            except ConnectionError:
                if net is self._net:
                    self._mark_dead()
                self._fail_all(ConnectionError(
                    f"replica {self.endpoint} connection lost"))
                return
            if msg is None:
                self._fail_all(ConnectionError("reader shut down"))
                return
            with self._lock:
                pend = self._pending.pop(msg.msg_id, None)
            if pend is None:
                continue  # cancelled (hedge loser) or unknown: drop
            latency = time.monotonic() - pend.t0
            self.latencies.append(latency)
            if msg.type in (MsgType.Reply_Read, MsgType.Reply_Query):
                try:
                    pend.cb(wire.decode(msg.data), int(msg.watermark), None)
                except Exception as exc:  # noqa: BLE001 — a decode bug must
                    # surface as a failed read, not kill the pump
                    pend.cb(None, -1, exc)
            elif msg.type == MsgType.Reply_Error:
                text = str(wire.decode(msg.data)) if msg.data else "error"
                if text.startswith("replica-refused"):
                    self.stale_until = (time.monotonic()
                                        + self.STALE_BACKOFF)
                    pend.cb(None, int(msg.watermark), _Refused(text))
                else:
                    pend.cb(None, -1, RuntimeError(text))
            else:
                pend.cb(None, -1,
                        RuntimeError(f"unexpected read reply {msg.type}"))


# -- scheduler (hedge timers + read deadlines) --------------------------------

class _Scheduler:
    """One timer thread per router: a heap of (when, fn) — hedge fires
    and per-attempt deadlines. Callbacks run on the timer thread and must
    be quick/non-blocking (they only flip attempt state and fire sends)."""

    def __init__(self) -> None:
        self._heap: List[Tuple[float, int, Callable]] = []
        self._seq = 0
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mv-read-timers")
        self._thread.start()

    def at(self, when: float, fn: Callable) -> None:
        with self._cv:
            self._seq += 1
            heapq.heappush(self._heap, (when, self._seq, fn))
            self._cv.notify()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._closed and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    if self._heap:
                        self._cv.wait(max(0.0, self._heap[0][0]
                                          - time.monotonic()))
                    else:
                        self._cv.wait()
                if self._closed:
                    return
                _, _, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 — timers must survive
                log.error("read scheduler callback failed: %r", exc)


# -- router -------------------------------------------------------------------

class ReadRouter:
    """Routes one client's Gets per the read preference: cache, then
    budget-admitted replicas (round-robin, hedged optionally), then the
    primary — transparently, so the caller's completion only ever fails
    if the PRIMARY path fails (the acceptance property of the
    replica-kill drill)."""

    def __init__(self, endpoints: List[str], preference: str,
                 primary_submit: Callable[[int, Any, Any], None],
                 budget: Optional[int] = None,
                 cache_bytes: Optional[int] = None,
                 req_id_source: Optional[Callable[[], int]] = None,
                 watermark_confirm: Optional[Callable[[int], None]] = None,
                 retry_budget: Optional[object] = None,
                 primary_query_submit: Optional[
                     Callable[[int, Any, Any], None]] = None) -> None:
        self.preference = validate_read_preference(preference)
        # shared per-connection retry budget (fault/retry.py RetryBudget
        # or None): hedges are retries in the budget's ledger — a dry
        # bucket skips the hedge (the first fire still runs), so hedging
        # pressure decays with the success rate under overload
        self.retry_budget = retry_budget
        self.budget = int(budget if budget is not None
                          else config.get_flag("read_staleness_records"))
        self._primary_submit = primary_submit
        # queries fall back through their own primary leg (a direct
        # Request_Query, not a Get); None = queries are not routable
        # through this router and submit_query refuses loudly
        self._primary_query_submit = primary_query_submit
        # Tracing seams (both optional so bare routers stay valid): a
        # req_id source makes every routed Get a traced span; the
        # watermark-confirm callback fires after a REPLICA-served success
        # so the primary records a hop under the same span (the stitched
        # trace's third process) and re-advertises its append watermark.
        self._req_id_source = req_id_source
        self._watermark_confirm = watermark_confirm
        self._readers = [ReplicaReader(e) for e in endpoints]
        self._rr = 0
        self._rr_lock = threading.Lock()
        cap = int(cache_bytes if cache_bytes is not None
                  else config.get_flag("client_cache_bytes"))
        self.cache = ReadCache(cap) if cap > 0 else None
        self.timeout = float(config.get_flag("read_timeout_seconds"))
        # hedge delay pin: cached for the hot path but kept LIVE through
        # the config watch seam — a runtime set_flag("read_hedge_ms")
        # (operator or autotuner) takes effect on the next hedge instead
        # of being silently ignored until the router is rebuilt
        self._hedge_ms = float(config.get_flag("read_hedge_ms"))
        self._unsubscribe = [config.FLAGS.on_change(
            "read_hedge_ms", self._on_hedge_ms_change)]
        if cache_bytes is None:
            # the cache capacity is flag-derived too: grow/shrink/create
            # it live (an explicit constructor cap stays pinned)
            self._unsubscribe.append(config.FLAGS.on_change(
                "client_cache_bytes", self._on_cache_bytes_change))
        self._scheduler = _Scheduler()

    def _on_hedge_ms_change(self, _name: str, value) -> None:
        self._hedge_ms = float(value)

    def _on_cache_bytes_change(self, _name: str, value) -> None:
        cap = int(value)
        cache = self.cache
        if cap <= 0:
            self.cache = None
        elif cache is None:
            self.cache = ReadCache(cap)
        else:
            cache.resize(cap)

    def close(self) -> None:
        for unsub in getattr(self, "_unsubscribe", ()):
            unsub()
        self._unsubscribe = []
        self._scheduler.close()
        for reader in self._readers:
            reader.close()

    # -- policy helpers ------------------------------------------------------
    def active(self) -> bool:
        return self.preference != "primary" and bool(self._readers)

    def note_local_write(self, table_id: int) -> None:
        if self.cache is not None:
            self.cache.invalidate_table(table_id)

    def observe_primary_watermark(self, watermark: int) -> None:
        if self.cache is not None:
            self.cache.observe_primary(watermark)

    def next_reader(self, exclude: List[ReplicaReader]
                    ) -> Optional[ReplicaReader]:
        now = time.monotonic()
        with self._rr_lock:
            n = len(self._readers)
            for i in range(n):
                reader = self._readers[(self._rr + i) % n]
                if reader not in exclude and reader.available(now):
                    self._rr = (self._rr + i + 1) % n
                    return reader
        return None

    def hedge_delay(self) -> float:
        """p95 of recent replica read latencies (pooled), clamped to
        [1 ms, read_timeout]; the read_hedge_ms flag pins it. The
        derived value is exported as the READ_HEDGE_DELAY_SECONDS gauge
        — the effective hedging posture operators (and the autopilot's
        pressure sensors) read."""
        if self._hedge_ms > 0:
            delay = min(self._hedge_ms / 1000.0, self.timeout)
        else:
            samples: List[float] = []
            for reader in self._readers:
                samples.extend(reader.latencies)
            if not samples:
                delay = min(0.01, self.timeout)
            else:
                samples.sort()
                p95 = samples[min(len(samples) - 1,
                                  int(0.95 * len(samples)))]
                delay = max(0.001, min(p95, self.timeout))
        gauge_set("READ_HEDGE_DELAY_SECONDS", delay)
        return delay

    # -- entry point ---------------------------------------------------------
    def submit_get(self, table_id: int, request: Any, completion) -> int:
        """Serve one Get through the read tier. Settles ``completion``
        exactly once — from the cache, a replica, or the primary
        fallback. Returns the span's req_id (0 untraced) so callers a
        layer up — the shard router — can append their own hops."""
        req_id = self._req_id_source() if self._req_id_source else 0
        hop(req_id, "client_read_submit")
        tag_tenant(req_id, resolve_tenant(table_id))
        key = (cache_key(table_id, request)
               if self.cache is not None else None)
        if key is not None:
            hit = self.cache.lookup(key, self.budget)
            if hit is not None:
                count("READ_CACHE_HITS")
                hop(req_id, "client_read_cache_hit")
                completion.done(hit)
                return req_id
            count("READ_CACHE_MISSES")
        _ReadAttempt(self, table_id, request, key, completion,
                     req_id).start()
        return req_id

    def submit_query(self, table_id: int, request: Any, completion) -> int:
        """Serve one top-k query (``Request_Query``) through the same
        cache → replica → primary ladder as :meth:`submit_get`, counted
        under ``QUERY_*`` so retrieval traffic reads apart from training
        Gets on a dashboard. The cache key is namespaced under a
        ``"query"`` sentinel — (query bytes, k, metric) can never
        collide with a Get entry — and write-through invalidation,
        lease expiry and watermark aging apply unchanged."""
        if self._primary_query_submit is None:
            completion.fail(RuntimeError(
                "read tier has no primary query leg (router built "
                "without primary_query_submit)"))
            return 0
        req_id = self._req_id_source() if self._req_id_source else 0
        hop(req_id, "client_query_submit")
        tag_tenant(req_id, resolve_tenant(table_id))
        key = (cache_key(table_id, ("query", request))
               if self.cache is not None else None)
        if key is not None:
            hit = self.cache.lookup(key, self.budget)
            if hit is not None:
                count("QUERY_CACHE_HITS")
                hop(req_id, "client_query_cache_hit")
                completion.done(hit)
                return req_id
            count("QUERY_CACHE_MISSES")
        _ReadAttempt(self, table_id, request, key, completion,
                     req_id, query=True).start()
        return req_id


class _ReadAttempt:
    """One routed Get's life: replica attempts, the hedge, deadlines,
    and the primary fallback — settled exactly once."""

    __slots__ = ("_router", "_table_id", "_request", "_key", "_completion",
                 "_lock", "_settled", "_tried", "_inflight", "_hedged",
                 "_fell_back", "_req_id", "_query")

    def __init__(self, router: ReadRouter, table_id: int, request: Any,
                 key: Optional[Tuple], completion,
                 req_id: int = 0, query: bool = False) -> None:
        self._router = router
        self._table_id = table_id
        self._request = request
        self._key = key
        self._completion = completion
        self._req_id = int(req_id)
        self._query = bool(query)
        self._lock = threading.Lock()
        self._settled = False
        # queue depth of the read tier: attempts alive between submit
        # and settle. The exactly-once settle path is the exactly-once
        # decrement, so the gauge can never drift negative.
        gauge_add("READ_INFLIGHT", 1)
        self._tried: List[ReplicaReader] = []
        # live (reader, token) pairs — cancelled when someone wins
        self._inflight: List[Tuple[ReplicaReader, int]] = []
        self._hedged = False
        self._fell_back = False

    # -- firing --------------------------------------------------------------
    def start(self) -> None:
        if not self._fire_next():
            self._fallback()
            return
        if self._router.preference == "hedged":
            delay = self._router.hedge_delay()
            self._router._scheduler.at(time.monotonic() + delay,
                                       self._hedge_fire)

    def _fire_next(self) -> bool:
        """Fire the next untried, available replica; False when none."""
        reader = self._router.next_reader(self._tried)
        if reader is None:
            return False
        self._tried.append(reader)
        hop(self._req_id, "client_replica_send")
        token = reader.read_async(
            self._table_id, self._request, self._router.budget,
            lambda result, wm, err, reader=reader:
                self._on_reply(reader, result, wm, err),
            req_id=self._req_id, trace=bool(self._req_id),
            query=self._query)
        if token is None:
            return self._fire_next()  # send failed; try another
        with self._lock:
            if self._settled:
                reader.cancel(token)
                return True
            self._inflight.append((reader, token))
        self._router._scheduler.at(
            time.monotonic() + self._router.timeout,
            lambda reader=reader, token=token:
                self._on_deadline(reader, token))
        return True

    def _hedge_fire(self) -> None:
        with self._lock:
            if self._settled or self._hedged:
                return
            self._hedged = True
        budget = self._router.retry_budget
        if budget is not None and not budget.allow():
            return  # dry retry budget: the first fire keeps running,
            # only the speculative second copy is skipped (denial counted
            # by the budget)
        if self._query:
            count("QUERY_HEDGES")
        else:
            count("READ_HEDGES")
        if not self._fire_next():
            # no second replica available: hedge against the primary
            self._fallback(hedge=True)

    # -- settling ------------------------------------------------------------
    def _settle(self, result: Any = None,
                error: Optional[BaseException] = None,
                winner: Optional[Tuple[ReplicaReader, int]] = None) -> bool:
        with self._lock:
            if self._settled:
                return False
            self._settled = True
            losers = [p for p in self._inflight if p != winner]
            self._inflight.clear()
        gauge_add("READ_INFLIGHT", -1)
        for reader, token in losers:
            reader.cancel(token)
        if error is not None:
            self._completion.fail(error)
        else:
            self._completion.done(result)
        return True

    def _on_reply(self, reader: ReplicaReader, result: Any,
                  watermark: int, error: Optional[BaseException]) -> None:
        if error is None:
            router = self._router
            if router.cache is not None:
                router.cache.observe_replica(watermark)
                if self._key is not None:
                    router.cache.store(self._key, result, watermark)
            if self._settle(result=result,
                            winner=self._find_pair(reader)):
                if self._query:
                    count("QUERIES_VIA_REPLICA")
                else:
                    count("READS_VIA_REPLICA")
                hop(self._req_id, "client_read_reply")
                confirm = router._watermark_confirm
                if confirm is not None and self._req_id:
                    # replica-served span: ask the primary to stamp a
                    # watermark hop under the same req_id (the stitched
                    # trace's third process)
                    confirm(self._req_id)
                if self._hedged and len(self._tried) > 1 \
                        and reader is self._tried[-1]:
                    if self._query:
                        count("QUERY_HEDGE_WINS")
                    else:
                        count("READ_HEDGE_WINS")
            return
        if isinstance(error, _Refused):
            if self._query:
                count("QUERY_REPLICA_REFUSALS_SEEN")
            else:
                count("READ_REPLICA_REFUSALS_SEEN")
        with self._lock:
            if self._settled:
                return
            self._inflight = [p for p in self._inflight
                              if p[0] is not reader]
        if not self._fire_next():
            self._fallback()

    def _find_pair(self, reader: ReplicaReader
                   ) -> Optional[Tuple[ReplicaReader, int]]:
        with self._lock:
            for pair in self._inflight:
                if pair[0] is reader:
                    return pair
        return None

    def _on_deadline(self, reader: ReplicaReader, token: int) -> None:
        with self._lock:
            if self._settled or (reader, token) not in self._inflight:
                return
            self._inflight.remove((reader, token))
        reader.cancel(token)
        if self._query:
            count("QUERY_REPLICA_TIMEOUTS")
        else:
            count("READ_REPLICA_TIMEOUTS")
        if not self._fire_next():
            self._fallback()

    def _fallback(self, hedge: bool = False) -> None:
        """Route through the primary's normal Get path (its retry/
        reconnect machinery included) — the caller's completion fails
        only if THIS fails."""
        with self._lock:
            if self._settled or self._fell_back:
                return
            self._fell_back = True
        if self._query:
            count("QUERY_PRIMARY_FALLBACKS")
        else:
            count("READ_PRIMARY_FALLBACKS")
        # The primary path mints its own req_id (primary_submit's 3-arg
        # contract predates tracing); this hop marks the span break so a
        # collector knows the read continued under a fresh id.
        hop(self._req_id, "client_read_fallback")

        class _Settle:
            __slots__ = ("_attempt",)

            def __init__(self, attempt: "_ReadAttempt") -> None:
                self._attempt = attempt

            def done(self, result: Any) -> None:
                self._attempt._settle(result=result)

            def fail(self, error: BaseException) -> None:
                self._attempt._settle(error=error)

        submit = (self._router._primary_query_submit if self._query
                  else self._router._primary_submit)
        try:
            submit(self._table_id, self._request, _Settle(self))
        except Exception as exc:  # noqa: BLE001 — the submit itself died
            self._settle(error=exc)
