"""Host-side network transport + collectives for external (off-mesh) clients.

Reference capability (not copied): the ``NetInterface`` seam with MPI/ZMQ
backends (``include/multiverso/net.h:15-49``, ``net/mpi_net.h``,
``net/zmq_net.h``) and the hand-rolled ``AllreduceEngine``
(``include/multiverso/net/allreduce_engine.h:80-168``).

TPU-era role: ON the mesh, worker↔server traffic is XLA collectives over
ICI — no host transport exists and the Bruck/recursive-halving algorithm
choice is XLA's job (SURVEY §2.2). What survives is the OFF-mesh surface the
reference served with ZMQ's explicit Bind/Connect mode: external CPU-resident
clients (C-API hosts, data feeders, multi-process CPU deployments without a
JAX distributed runtime) that need rank-to-rank messaging and host
collectives. This module provides that: a TCP transport with the reference's
message framing semantics (typed header + length-prefixed blobs) and a ring
allreduce/allgather engine built on the raw send/recv channel.

Two channels per peer, like the reference's split between mailbox traffic
(``Send/Recv`` via the Communicator) and raw blocking transfers
(``SendTo/RecvFrom/SendRecv`` used by the AllreduceEngine):

* channel 0 — mailbox: frames land in a shared recv queue (``recv()``)
* channel 1 — raw: frames land in a per-peer queue (``recv_from(rank)``)
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiverso_tpu import log
from multiverso_tpu.dashboard import count, observe
from multiverso_tpu.obs.trace import flight_dump, hop
from multiverso_tpu.runtime.message import Message, MsgType
from multiverso_tpu.utils import MtQueue

_MAGIC = 0x4D565450  # 'MVTP'
# Wire version — the ONE place the frame layout is bumped. v2 grew the
# req_id field (idempotent replay, fault/retry.py); v3 grew payload_len +
# a CRC32 over the blob section, so a corrupted frame is detected and
# DISCARDED (the length keeps the stream in sync; retransmit + the dedup
# window recover the frame) instead of desyncing on a garbled blob size.
# Both sides of every deployment ship from this repo, so a mismatch is a
# config error and the connection is dropped loudly rather than negotiated.
_VERSION = 3
# magic, version, channel, src, dst, type, table, msg_id, req_id, nblobs,
# payload_len, crc32(payload)
_HEADER = struct.Struct("<IBBiiiiqqiqI")
_BLOB = struct.Struct("<B8sq")  # ndim, dtype str (padded), nbytes


def _pack_blob(arr: np.ndarray) -> Tuple[bytes, bytes]:
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode()[:8].ljust(8, b" ")
    payload = arr.tobytes()
    head = _BLOB.pack(arr.ndim, dt, len(payload)) + struct.pack(
        f"<{arr.ndim}q", *arr.shape)
    return head, payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def get_local_ip() -> str:
    """Best-effort local IP (reference net_util::GetLocalIPAddress parity)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def parse_machine_file(path: str) -> List[str]:
    """One ``host[:port]`` per line; rank = line index (zmq_net.h machine-file
    contract). Default port from the ``port`` flag."""
    from multiverso_tpu.config import get_flag
    endpoints = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" not in line:
                line = f"{line}:{get_flag('port')}"
            endpoints.append(line)
    return endpoints


class TcpNet:
    """Rank-to-rank TCP transport with explicit Bind/Connect (the reference
    ZMQ backend's raw-net mode for external hosts)."""

    def __init__(self) -> None:
        self.rank = -1
        self.size = 0
        self._endpoints: List[str] = []
        self._listener: Optional[socket.socket] = None
        self._conns: Dict[int, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._send_locks: Dict[int, threading.Lock] = {}
        self._sock_locks: Dict[socket.socket, threading.Lock] = {}
        self._mailbox: MtQueue = MtQueue()
        self._raw: Dict[int, MtQueue] = {}
        self._accept_thread: Optional[threading.Thread] = None
        self._accepted: list = []
        self._active = False

    # -- lifecycle ----------------------------------------------------------
    def bind(self, rank: int, endpoint: str) -> str:
        """Listen on ``host:port`` (port 0 → ephemeral); returns the bound
        endpoint (MV_NetBind parity)."""
        host, port = endpoint.rsplit(":", 1)
        self.rank = rank
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        # wildcard/loopback binds must advertise a dialable address
        adv_host = get_local_ip() if host in ("0.0.0.0", "::", "") else host
        bound = f"{adv_host}:{self._listener.getsockname()[1]}"
        self._active = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"mvtpu-net-accept-{rank}")
        self._accept_thread.start()
        return bound

    def connect(self, endpoints: Sequence[str]) -> None:
        """Record the full rank→endpoint map (MV_NetConnect parity).
        Connections are dialed lazily on first send."""
        self._endpoints = list(endpoints)
        self.size = len(endpoints)
        for r in range(self.size):
            self._raw.setdefault(r, MtQueue())

    def init(self, rank: int, endpoints: Sequence[str]) -> None:
        """bind + connect in one step (symmetric deployments)."""
        self.bind(rank, endpoints[rank])
        self.connect(endpoints)

    def finalize(self) -> None:
        self._active = False
        if self._listener is not None:
            # shutdown() first: close() alone leaves the accept thread
            # blocked inside accept(), and that in-flight syscall pins the
            # open file description — the port would stay in LISTEN and a
            # server restart could not rebind it (fault recovery path)
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            for sock in list(self._conns.values()) + self._accepted:
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
            self._accepted.clear()
        self._mailbox.exit()
        for q in self._raw.values():
            q.exit()

    # -- send ---------------------------------------------------------------
    def send(self, msg: Message) -> int:
        return self._send(msg, channel=0)

    def send_to(self, rank: int, blobs: List[np.ndarray]) -> int:
        msg = Message(src=self.rank, dst=rank, type=MsgType.Request_Get,
                      data=blobs)
        return self._send(msg, channel=1)

    def recv(self) -> Optional[Message]:
        """Pop the next mailbox message (blocks; None on shutdown). Raises
        ConnectionError when a peer connection died while the transport is
        live (fail-fast instead of hanging waiters)."""
        msg = self._mailbox.pop()
        if (msg is not None and msg.type == MsgType.Reply_Error
                and msg.src == -1):
            raise ConnectionError("net: peer connection lost")
        return msg

    def recv_from(self, rank: int) -> Optional[List[np.ndarray]]:
        msg = self._raw[rank].pop()
        if msg is None:
            return None
        if msg.type == MsgType.Reply_Error and msg.src == -1:
            raise ConnectionError(
                "net: peer connection lost while waiting for data")
        return msg.data

    def send_recv(self, dst: int, blobs: List[np.ndarray],
                  src: int) -> Optional[List[np.ndarray]]:
        self.send_to(dst, blobs)
        return self.recv_from(src)

    def send_via(self, conn: socket.socket, msg: Message,
                 channel: int = 0) -> int:
        """Send over an explicit connection — the reply path for peers that
        never bound a listener (remote table clients): the server answers
        over the socket the request arrived on (``msg._conn``)."""
        return self._send_via_raw(conn, self._frame(msg, channel))

    # -- internals ----------------------------------------------------------
    @staticmethod
    def _frame(msg: Message, channel: int) -> bytes:
        t0 = time.perf_counter()
        parts = []
        for arr in msg.data:
            head, payload = _pack_blob(np.asarray(arr))
            parts.append(head)
            parts.append(payload)
        payload = b"".join(parts)
        header = _HEADER.pack(_MAGIC, _VERSION, channel, msg.src, msg.dst,
                              int(msg.type), msg.table_id, msg.msg_id,
                              msg.req_id, len(msg.data), len(payload),
                              zlib.crc32(payload))
        observe("FRAME_ENCODE_SECONDS", time.perf_counter() - t0)
        return header + payload

    def _send(self, msg: Message, channel: int) -> int:
        return self._send_raw(msg.dst, self._frame(msg, channel))

    def _send_raw(self, dst: int, frame: bytes) -> int:
        """Framed-bytes send seam: ChaosNet's ``corrupt`` action flips bits
        in an already-built frame and ships it through here."""
        sock = self._socket_for(dst)
        with self._send_locks.setdefault(dst, threading.Lock()):
            sock.sendall(frame)
        return len(frame)

    def _send_via_raw(self, conn: socket.socket, frame: bytes) -> int:
        with self._conn_lock:
            lock = self._sock_locks.setdefault(conn, threading.Lock())
        with lock:
            conn.sendall(frame)
        return len(frame)

    def _socket_for(self, rank: int) -> socket.socket:
        with self._conn_lock:
            sock = self._conns.get(rank)
        if sock is not None:
            return sock
        if not (0 <= rank < len(self._endpoints)):
            log.fatal("net: no endpoint for rank %d", rank)
        host, port = self._endpoints[rank].rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=30)
        # the connect timeout must not linger as an IO timeout: an idle
        # connection's recv loop would otherwise die after 30s of silence
        # and fake a peer loss
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._conn_lock:
            # keep the first established connection per peer
            existing = self._conns.get(rank)
            if existing is not None:
                sock.close()
                return existing
            self._conns[rank] = sock
        self._active = True
        # dialed sockets also receive: peers without a listener of their own
        # (remote table clients) get replies back over this connection
        threading.Thread(target=self._recv_loop, args=(sock,), daemon=True,
                         name=f"mvtpu-net-recv-dial-{self.rank}").start()
        return sock

    def _accept_loop(self) -> None:
        while self._active:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._accepted.append(conn)
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True,
                             name=f"mvtpu-net-recv-{self.rank}").start()

    def _recv_loop(self, conn: socket.socket) -> None:
        srcs_seen: set = set()
        try:
            while self._active:
                head = _read_exact(conn, _HEADER.size)
                (magic, version, channel, src, dst, mtype, table_id, msg_id,
                 req_id, nblobs, payload_len, crc) = _HEADER.unpack(head)
                if magic != _MAGIC:
                    log.error("net: bad frame magic %x", magic)
                    self._drop_conn(conn, srcs_seen)
                    return
                if version != _VERSION:
                    log.error("net: wire version %d from peer (want %d)",
                              version, _VERSION)
                    self._drop_conn(conn, srcs_seen)
                    return
                srcs_seen.add(src)
                # the header's payload_len keeps the stream in sync even
                # when the payload is garbage: read it all, checksum, and
                # only then parse blob structure out of it
                payload = _read_exact(conn, payload_len) if payload_len \
                    else b""
                if zlib.crc32(payload) != crc:
                    count("FRAME_CRC_REJECTS")
                    log.error("net: CRC mismatch on %s frame from %d — "
                              "frame discarded (retransmit recovers it)",
                              MsgType(mtype), src)
                    hop(req_id, "net_crc_reject")
                    flight_dump("frame_crc_reject", src=src,
                                msg_type=int(mtype), req_id=req_id)
                    continue
                t0 = time.perf_counter()
                off = 0
                blobs = []
                for _ in range(nblobs):
                    ndim, dt, nbytes = _BLOB.unpack_from(payload, off)
                    off += _BLOB.size
                    shape = struct.unpack_from(f"<{ndim}q", payload, off)
                    off += 8 * ndim
                    dtype = np.dtype(dt.decode().strip())
                    blobs.append(np.frombuffer(
                        payload, dtype=dtype, count=nbytes // dtype.itemsize,
                        offset=off).reshape(shape).copy())
                    off += nbytes
                observe("FRAME_DECODE_SECONDS", time.perf_counter() - t0)
                hop(req_id, "net_recv")
                msg = Message(src=src, dst=dst, type=MsgType(mtype),
                              table_id=table_id, msg_id=msg_id,
                              req_id=req_id, data=blobs)
                msg._conn = conn  # reply path for listener-less peers
                if channel == 1:
                    self._raw.setdefault(src, MtQueue()).push(msg)
                else:
                    self._mailbox.push(msg)
        except (ConnectionError, OSError):
            self._drop_conn(conn, srcs_seen)
            return

    def _drop_conn(self, conn: socket.socket, srcs_seen: set) -> None:
        """A connection died: prune its bookkeeping and — if the transport
        is still live — push a peer-lost sentinel so blocked receivers
        (mid-allreduce, pending table replies) fail fast instead of hanging
        until finalize(). Only the dead peer's raw queues are poisoned."""
        with self._conn_lock:
            self._sock_locks.pop(conn, None)
            if conn in self._accepted:
                self._accepted.remove(conn)
            for rank, sock in list(self._conns.items()):
                if sock is conn:
                    del self._conns[rank]
                    srcs_seen = srcs_seen | {rank}
        try:
            conn.close()
        except OSError:
            pass
        if not self._active:
            return  # normal shutdown; finalize() exits the queues
        sentinel = Message(src=-1, dst=self.rank, type=MsgType.Reply_Error)
        sentinel._conn = conn
        self._mailbox.push(sentinel)
        for src in srcs_seen:
            q = self._raw.get(src)
            if q is not None:
                q.push(sentinel)


class AllreduceEngine:
    """Host collectives over the raw channel (reference AllreduceEngine
    capability). On-mesh the algorithm choice (Bruck allgather /
    recursive-halving reduce-scatter) belongs to XLA; here a ring
    reduce-scatter + ring allgather covers the host path, which is
    latency-dominated at external-client scales."""

    def __init__(self, net: TcpNet) -> None:
        self.net = net

    def allreduce(self, data: np.ndarray) -> np.ndarray:
        """Elementwise sum across all ranks; every rank gets the result."""
        n, r = self.net.size, self.net.rank
        if n <= 1:
            return np.asarray(data).copy()
        flat = np.asarray(data).reshape(-1)
        pad = (-flat.size) % n
        work = np.concatenate([flat, np.zeros(pad, flat.dtype)])
        chunks = np.split(work.copy(), n)
        right = (r + 1) % n
        left = (r - 1) % n
        # ring reduce-scatter: after n-1 steps chunk (r+1)%n is fully reduced
        for step in range(n - 1):
            send_idx = (r - step) % n
            recv_idx = (r - step - 1) % n
            got = self.net.send_recv(right, [chunks[send_idx]], left)
            if got is None:
                log.fatal("allreduce: transport shut down mid-collective")
            chunks[recv_idx] = chunks[recv_idx] + got[0]
        # ring allgather of the reduced chunks
        for step in range(n - 1):
            send_idx = (r - step + 1) % n
            recv_idx = (r - step) % n
            got = self.net.send_recv(right, [chunks[send_idx]], left)
            if got is None:
                log.fatal("allreduce: transport shut down mid-collective")
            chunks[recv_idx] = got[0]
        out = np.concatenate(chunks)
        if pad:
            out = out[:flat.size]
        return out.reshape(np.asarray(data).shape)

    def allgather(self, data: np.ndarray) -> List[np.ndarray]:
        """Every rank's array, in rank order (reference Allgather parity)."""
        n, r = self.net.size, self.net.rank
        parts: List[Optional[np.ndarray]] = [None] * n
        parts[r] = np.asarray(data).copy()
        right = (r + 1) % n
        left = (r - 1) % n
        for step in range(n - 1):
            send_idx = (r - step) % n
            got = self.net.send_recv(right, [parts[send_idx]], left)
            if got is None:
                log.fatal("allgather: transport shut down mid-collective")
            parts[(r - step - 1) % n] = got[0]
        return parts  # type: ignore[return-value]
