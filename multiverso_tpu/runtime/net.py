"""Host-side network transport + collectives for external (off-mesh) clients.

Reference capability (not copied): the ``NetInterface`` seam with MPI/ZMQ
backends (``include/multiverso/net.h:15-49``, ``net/mpi_net.h``,
``net/zmq_net.h``) and the hand-rolled ``AllreduceEngine``
(``include/multiverso/net/allreduce_engine.h:80-168``).

TPU-era role: ON the mesh, worker↔server traffic is XLA collectives over
ICI — no host transport exists and the Bruck/recursive-halving algorithm
choice is XLA's job (SURVEY §2.2). What survives is the OFF-mesh surface the
reference served with ZMQ's explicit Bind/Connect mode: external CPU-resident
clients (C-API hosts, data feeders, multi-process CPU deployments without a
JAX distributed runtime) that need rank-to-rank messaging and host
collectives. This module provides that: a TCP transport with the reference's
message framing semantics (typed header + length-prefixed blobs) and a ring
allreduce/allgather engine built on the raw send/recv channel.

Two channels per peer, like the reference's split between mailbox traffic
(``Send/Recv`` via the Communicator) and raw blocking transfers
(``SendTo/RecvFrom/SendRecv`` used by the AllreduceEngine):

* channel 0 — mailbox: frames land in a shared recv queue (``recv()``)
* channel 1 — raw: frames land in a per-peer queue (``recv_from(rank)``)
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu.dashboard import count, gauge_add, observe
from multiverso_tpu.obs.profiler import clear_wait, mark_wait
from multiverso_tpu.obs.trace import flight_dump, hop
from multiverso_tpu.runtime.message import Message, MsgType
from multiverso_tpu.runtime.shm import ShmChannel
from multiverso_tpu.utils import MtQueue

_MAGIC = 0x4D565450  # 'MVTP'
# Wire version — the ONE place the frame layout is bumped. v2 grew the
# req_id field (idempotent replay, fault/retry.py); v3 grew payload_len +
# a CRC32 over the blob section, so a corrupted frame is detected and
# DISCARDED (the length keeps the stream in sync; retransmit + the dedup
# window recover the frame) instead of desyncing on a garbled blob size;
# v4 grew the watermark field (read-replica tier: WAL record sequence on
# replies/records, staleness budget on Request_Read frames); v5 grew the
# deadline budget field — the REMAINING microseconds a request's caller
# will keep waiting (0 = no deadline, never refused). A budget, not an
# instant: each receiver re-anchors it against its own monotonic clock
# (wall-clock skew between hosts cannot expire a request), and each hop
# that re-encodes the frame ships only what's left after its own queueing,
# so the budget decrements across hops for free.
# Both sides of every deployment ship from this repo, so a mismatch is a
# config error and the connection is dropped loudly rather than negotiated.
_VERSION = 5
# magic, version, channel, src, dst, type, table, msg_id, req_id,
# watermark, deadline_us, nblobs, payload_len, crc32(payload)
_HEADER = struct.Struct("<IBBiiiiqqqiiqI")
_BLOB = struct.Struct("<B8sq")  # ndim, dtype str (padded), nbytes

# One vectored syscall carries at most this many iovec segments — well
# under Linux's IOV_MAX (1024) so sendmsg never rejects a batch.
_IOV_MAX_SEGS = 512
# Batches at or below this many bytes are joined into ONE contiguous
# buffer before the syscall: copying a few KiB is cheaper than carrying
# dozens of iovec entries through the kernel. Zero-copy only pays once
# the payload dwarfs the copy cost.
_JOIN_BYTES = 1 << 16
# Producer backpressure: a connection's outgoing queue holds at most this
# many multiples of wire_coalesce_bytes before senders block (a dead-slow
# peer must not buffer unbounded frames in the process).
_QUEUE_CAP_MULT = 8


def _tune_socket(sock: socket.socket, buf_bytes: int = 1 << 20) -> None:
    """The ONE socket-tuning site (data plane and multihost control plane
    both call it): latency first (TCP_NODELAY — frames are latency-bound
    RPCs, coalescing happens above the socket, not in Nagle), then
    throughput (SO_SNDBUF/SO_RCVBUF sized for a full coalesced batch so a
    vectored flush lands in one kernel pass)."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, int(buf_bytes))
        except OSError:
            pass  # platform cap — the default sizing still applies


def _pack_blob(arr: np.ndarray) -> Tuple[bytes, memoryview, int]:
    """-> (head bytes, payload buffer, payload nbytes). The payload is a
    memoryview over the array's own memory — never ``tobytes()`` — so
    large Add/Get payloads cross the send path without a Python-side
    copy (the memoryview keeps any ascontiguousarray temporary alive)."""
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.str.encode()[:8].ljust(8, b" ")
    head = _BLOB.pack(arr.ndim, dt, arr.nbytes) + struct.pack(
        f"<{arr.ndim}q", *arr.shape)
    return head, memoryview(arr).cast("B"), arr.nbytes


class _WireDesync(ConnectionError):
    """The stream produced an unparsable header (bad magic / version):
    nothing downstream can be trusted — the connection must drop."""


class _Frame:
    """One queued outbound frame: its iovec segments plus completion
    state (``done``/``error``) the drain loop reports back through."""

    __slots__ = ("segments", "nbytes", "done", "error")

    def __init__(self, segments: List[Any], nbytes: int) -> None:
        self.segments = segments
        self.nbytes = nbytes
        self.done = False
        self.error: Optional[BaseException] = None


_send_metrics_cache = None


def _send_metrics():
    """Send-path metric objects, resolved ONCE: the registry's global
    lock must not sit on the per-frame hot path (Dashboard.reset zeroes
    objects in place, so cached references stay live)."""
    global _send_metrics_cache
    if _send_metrics_cache is None:
        from multiverso_tpu.dashboard import Dashboard
        _send_metrics_cache = (Dashboard.counter("SEND_SYSCALLS"),
                               Dashboard.counter("SEND_COALESCED_FRAMES"),
                               Dashboard.counter("SEND_COALESCED_BYTES"),
                               Dashboard.histogram("WIRE_FRAMES_PER_SYSCALL"),
                               Dashboard.gauge("SEND_QUEUE_BYTES"))
    return _send_metrics_cache


class _SendState:
    """Per-socket outgoing state: the legacy per-frame send lock plus —
    in coalescing mode — the frame deque a dedicated drain thread
    flushes in vectored batches. ``held`` freezes the drain (tests and
    deterministic-coalescing harnesses force a burst through it)."""

    __slots__ = ("lock", "cv", "frames", "bytes", "closed", "error", "held",
                 "draining")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # plain Lock under the Condition: the default RLock's ownership
        # bookkeeping is measurable on the per-frame path
        self.cv = threading.Condition(threading.Lock())
        self.frames: deque = deque()
        self.bytes = 0
        self.closed = False
        self.error: Optional[BaseException] = None
        self.held = False
        # True while exactly one sender (inline caller or the drain
        # thread) is mid-batch — the exclusivity that keeps the stream
        # ordered without a lock held across the syscall
        self.draining = False


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    # profiler wait site: time parked in recv is wire/peer wait, not CPU
    prev = mark_wait("net_recv")
    try:
        while n > 0:
            chunk = sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("peer closed")
            chunks.append(chunk)
            n -= len(chunk)
    finally:
        clear_wait(prev)
    return b"".join(chunks)


def get_local_ip() -> str:
    """Best-effort local IP (reference net_util::GetLocalIPAddress parity)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def parse_machine_file(path: str) -> List[str]:
    """One ``host[:port]`` per line; rank = line index (zmq_net.h machine-file
    contract). Default port from the ``port`` flag."""
    from multiverso_tpu.config import get_flag
    endpoints = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" not in line:
                line = f"{line}:{get_flag('port')}"
            endpoints.append(line)
    return endpoints


class TcpNet:
    """Rank-to-rank TCP transport with explicit Bind/Connect (the reference
    ZMQ backend's raw-net mode for external hosts)."""

    def __init__(self) -> None:
        self.rank = -1
        self.size = 0
        self._endpoints: List[str] = []
        self._listener: Optional[socket.socket] = None
        self._conns: Dict[int, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._send_states: Dict[socket.socket, _SendState] = {}
        self._mailbox: MtQueue = MtQueue()
        self._raw: Dict[int, MtQueue] = {}
        self._accept_thread: Optional[threading.Thread] = None
        self._accepted: list = []
        self._active = False
        # coalescing caps: cached for the drain loop but LIVE through the
        # config watch seam, so a runtime step (operator or autotuner)
        # reshapes the next vectored send instead of waiting for a net
        # rebuild; 0 on either flag = legacy per-frame sendall. NOTE the
        # queue-vs-sendall mode itself stays as constructed — only the
        # caps of an already-coalescing net move (mode needs the queue
        # machinery wired at construction).
        self._coalesce_frames = int(config.get_flag("wire_coalesce_frames"))
        self._coalesce_bytes = int(config.get_flag("wire_coalesce_bytes"))
        self._coalesce = (self._coalesce_frames > 0
                          and self._coalesce_bytes > 0)
        self._flag_unsubs = [
            config.FLAGS.on_change("wire_coalesce_frames",
                                   self._on_coalesce_change),
            config.FLAGS.on_change("wire_coalesce_bytes",
                                   self._on_coalesce_change),
        ]
        # shared-memory transport (runtime/shm.py), negotiated per dialed
        # connection when the flag is on; keyed by the TCP socket that
        # carries the connection's liveness (server side: the accepted
        # conn the offer arrived on)
        self._shm_enabled = bool(config.get_flag("wire_shm"))
        self._shm_bytes = int(config.get_flag("wire_shm_bytes"))
        self._shm_channels: Dict[Any, ShmChannel] = {}

    def _on_coalesce_change(self, _name: str, _value) -> None:
        # caps move live (the drain loop reads them per batch); the
        # queue-vs-sendall mode stays as constructed
        self._coalesce_frames = int(config.get_flag("wire_coalesce_frames"))
        self._coalesce_bytes = int(config.get_flag("wire_coalesce_bytes"))

    # -- lifecycle ----------------------------------------------------------
    def bind(self, rank: int, endpoint: str) -> str:
        """Listen on ``host:port`` (port 0 → ephemeral); returns the bound
        endpoint (MV_NetBind parity)."""
        host, port = endpoint.rsplit(":", 1)
        self.rank = rank
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        # wildcard/loopback binds must advertise a dialable address
        adv_host = get_local_ip() if host in ("0.0.0.0", "::", "") else host
        bound = f"{adv_host}:{self._listener.getsockname()[1]}"
        self._active = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"mvtpu-net-accept-{rank}")
        self._accept_thread.start()
        return bound

    def connect(self, endpoints: Sequence[str]) -> None:
        """Record the full rank→endpoint map (MV_NetConnect parity).
        Connections are dialed lazily on first send."""
        self._endpoints = list(endpoints)
        self.size = len(endpoints)
        for r in range(self.size):
            self._raw.setdefault(r, MtQueue())

    def init(self, rank: int, endpoints: Sequence[str]) -> None:
        """bind + connect in one step (symmetric deployments)."""
        self.bind(rank, endpoints[rank])
        self.connect(endpoints)

    def finalize(self) -> None:
        self._active = False
        for unsub in getattr(self, "_flag_unsubs", ()):
            unsub()
        self._flag_unsubs = []
        # flush queued frames BEFORE tearing connections down: callers
        # that enqueued (deregister, final replies) relied on sendall
        # semantics — give the drain loops a bounded window to empty
        self._flush_queues(timeout=1.0)
        # close negotiated shm channels: blocked ring peers fail fast and
        # each reader thread disposes its mappings on the way out
        with self._conn_lock:
            channels = list(self._shm_channels.values())
            self._shm_channels.clear()
        for ch in channels:
            ch.close()
        with self._conn_lock:
            states = list(self._send_states.values())
        for st in states:
            with st.cv:
                st.closed = True
                st.cv.notify_all()
        if self._listener is not None:
            # shutdown() first: close() alone leaves the accept thread
            # blocked inside accept(), and that in-flight syscall pins the
            # open file description — the port would stay in LISTEN and a
            # server restart could not rebind it (fault recovery path)
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            for sock in list(self._conns.values()) + self._accepted:
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()
            self._accepted.clear()
        self._mailbox.exit()
        for q in self._raw.values():
            q.exit()

    # -- send ---------------------------------------------------------------
    def send(self, msg: Message) -> int:
        return self._send(msg, channel=0)

    def send_to(self, rank: int, blobs: List[np.ndarray]) -> int:
        msg = Message(src=self.rank, dst=rank, type=MsgType.Request_Get,
                      data=blobs)
        return self._send(msg, channel=1)

    def recv(self) -> Optional[Message]:
        """Pop the next mailbox message (blocks; None on shutdown). Raises
        ConnectionError when a peer connection died while the transport is
        live (fail-fast instead of hanging waiters)."""
        msg = self._mailbox.pop()
        if (msg is not None and msg.type == MsgType.Reply_Error
                and msg.src == -1):
            raise ConnectionError("net: peer connection lost")
        return msg

    def recv_from(self, rank: int) -> Optional[List[np.ndarray]]:
        msg = self._raw[rank].pop()
        if msg is None:
            return None
        if msg.type == MsgType.Reply_Error and msg.src == -1:
            raise ConnectionError(
                "net: peer connection lost while waiting for data")
        return msg.data

    def send_recv(self, dst: int, blobs: List[np.ndarray],
                  src: int) -> Optional[List[np.ndarray]]:
        self.send_to(dst, blobs)
        return self.recv_from(src)

    def send_via(self, conn: socket.socket, msg: Message,
                 channel: int = 0, flush: bool = False) -> int:
        """Send over an explicit connection — the reply path for peers that
        never bound a listener (remote table clients): the server answers
        over the socket the request arrived on (``msg._conn``).
        ``flush=True`` blocks until the frame reached the kernel — the
        ordering barrier replication needs (a WAL record must hit the
        standby's socket before the client's ACK is even queued)."""
        segments, nbytes = self._frame_segments(msg, channel)
        return self._enqueue(conn, segments, nbytes, flush=flush)

    # -- internals ----------------------------------------------------------
    def _frame_segments(self, msg: Message,
                        channel: int) -> Tuple[List[Any], int]:
        """Vectored frame assembly: ``[header, blob-head, blob-payload,
        ...]`` where payloads are memoryviews over the original array
        memory. The CRC32 runs incrementally across the payload section,
        so the bytes on the wire are bit-identical to the legacy
        concatenated frame without ever materializing it."""
        t0 = time.perf_counter()
        segments: List[Any] = [b""]  # header lands here once CRC is known
        crc = 0
        payload_len = 0
        for arr in msg.data:
            head, payload, blob_bytes = _pack_blob(np.asarray(arr))
            crc = zlib.crc32(head, crc)
            segments.append(head)
            payload_len += len(head)
            if blob_bytes:
                crc = zlib.crc32(payload, crc)
                segments.append(payload)
                payload_len += blob_bytes
        # trace flag rides the channel byte's high bit (channels are tiny
        # small ints) — no header-layout change, v3-framed transports
        # (shm rings) inherit it for free
        wire_channel = channel | (0x80 if getattr(msg, "trace", False)
                                  else 0)
        # deadline rides as REMAINING budget (µs): measured against this
        # sender's clock at encode time, so queueing spent here is already
        # subtracted. An expired-at-encode deadline ships as the 1 µs
        # floor — the receiver drops it at drain with a truthful
        # deadline_exceeded instead of this layer silently eating it.
        deadline_us = 0
        local_deadline = getattr(msg, "deadline", 0.0)
        if local_deadline > 0:
            deadline_us = max(
                1, min(0x7FFFFFFF,
                       int((local_deadline - time.monotonic()) * 1e6)))
        segments[0] = _HEADER.pack(_MAGIC, _VERSION, wire_channel, msg.src,
                                   msg.dst, int(msg.type), msg.table_id,
                                   msg.msg_id, msg.req_id, msg.watermark,
                                   deadline_us, len(msg.data), payload_len,
                                   crc)
        observe("FRAME_ENCODE_SECONDS", time.perf_counter() - t0)
        return segments, _HEADER.size + payload_len

    def _frame(self, msg: Message, channel: int) -> bytes:
        """Contiguous frame bytes — the ChaosNet corrupt seam and golden
        tests want the materialized form; the hot path never builds it."""
        segments, _ = self._frame_segments(msg, channel)
        return b"".join(segments)

    def _send(self, msg: Message, channel: int) -> int:
        segments, nbytes = self._frame_segments(msg, channel)
        return self._enqueue(self._socket_for(msg.dst), segments, nbytes)

    def _send_raw(self, dst: int, frame: bytes) -> int:
        """Framed-bytes send seam: ChaosNet's ``corrupt`` action flips bits
        in an already-built frame and ships it through here. Rides the
        same per-socket queue as vectored frames, so a corrupted frame
        coalesces with its neighbors exactly like a healthy one."""
        return self._enqueue(self._socket_for(dst), [frame], len(frame))

    def _send_via_raw(self, conn: socket.socket, frame: bytes) -> int:
        return self._enqueue(conn, [frame], len(frame))

    # -- coalescing send queue ----------------------------------------------
    def _state_for(self, sock: socket.socket) -> _SendState:
        with self._conn_lock:
            st = self._send_states.get(sock)
            if st is None:
                st = self._send_states[sock] = _SendState()
            return st

    def _enqueue(self, sock: socket.socket, segments: List[Any],
                 nbytes: int, flush: bool = False) -> int:
        # shm divert: a negotiated connection's frames cross as ONE locked
        # memcpy into the ring — no queue, no syscall; writes are
        # synchronous (ring-full blocking = the sendall backpressure), so
        # ``flush`` is trivially satisfied. ``sock`` may BE the channel
        # (reply path for frames that arrived over the ring).
        if isinstance(sock, ShmChannel):
            return sock.send_segments(segments, nbytes)
        if self._shm_channels:
            ch = self._shm_channels.get(sock)
            if ch is not None:
                return ch.send_segments(segments, nbytes)
        st = self._state_for(sock)
        if not self._coalesce:
            # legacy posture (wire_coalesce_* = 0): one locked sendall
            # per frame, frame bytes materialized
            with st.lock:
                sock.sendall(b"".join(segments))
            _send_metrics()[0].add(1)
            return nbytes
        cap = max(self._coalesce_bytes * _QUEUE_CAP_MULT, 8 << 20)
        frame = None
        with st.cv:
            if st.bytes >= cap:
                # backpressure: block while the peer is this far behind —
                # the bound sendall's kernel buffer used to provide
                st.cv.wait_for(lambda: st.bytes < cap or st.closed
                               or st.error is not None)
            if st.error is not None:
                raise OSError(f"net: send failed earlier on this "
                              f"connection: {st.error!r}")
            if st.closed:
                raise OSError("net: transport closed")
            # fast path: the connection is idle — claim the drain token
            # and send INLINE on this thread, allocating nothing (the
            # single-outstanding-request case costs what a bare locked
            # sendall did). A send already in flight is exactly the
            # coalescing case: queue the frame for the current holder's
            # next batch.
            fast = not st.held and not st.draining and not st.frames
            if fast:
                st.draining = True
            else:
                frame = _Frame(segments, nbytes)
                st.frames.append(frame)
                st.bytes += nbytes
                _send_metrics()[4].add(nbytes)  # SEND_QUEUE_BYTES
                claim = not st.held and not st.draining
                if claim:
                    st.draining = True
        if fast:
            try:
                if nbytes <= _JOIN_BYTES:
                    sock.sendall(b"".join(segments))
                    syscalls = 1
                else:
                    syscalls = self._sendmsg_all(sock, segments)
            except OSError as exc:
                self._fail_send_state(st, exc)
                raise  # synchronous, exactly like the legacy sendall
            (syscalls_c, frames_c, bytes_c, fps_hist, _g) = _send_metrics()
            syscalls_c.add(syscalls)
            frames_c.add(1)
            bytes_c.add(nbytes)
            fps_hist.observe(1 / syscalls)
            with st.cv:
                st.draining = False
                # frames queued while our send was in flight: drain them
                # (coalesced) before releasing the token
                backlog = bool(st.frames) and not st.held \
                    and st.error is None
                if backlog:
                    st.draining = True
                st.cv.notify_all()
            if backlog:
                self._drain_pending(sock, st)
            return nbytes
        if claim:
            self._drain_pending(sock, st)
        if flush and not frame.done:
            with st.cv:
                st.cv.wait_for(lambda: frame.done
                               or frame.error is not None)
            if frame.error is not None:
                raise OSError(f"net: flush failed: {frame.error!r}")
        return nbytes

    def _fail_send_state(self, st: _SendState,
                         exc: BaseException) -> None:
        """Sticky-fail a connection's send state: every queued frame and
        future sender sees the error; flush/backpressure waiters wake."""
        with st.cv:
            st.error = exc
            st.draining = False
            for fr in st.frames:
                fr.error = exc
            st.frames.clear()
            _send_metrics()[4].add(-st.bytes)
            st.bytes = 0
            st.cv.notify_all()

    def _drain_pending(self, sock: socket.socket, st: _SendState) -> None:
        """Flush the queue in vectored batches until empty — the drain
        loop. Caller must hold the ``draining`` token; frames other
        threads queue while a batch is in flight are picked up by the
        re-check before the token is released, so every frame queued
        behind an in-flight send rides ONE sendmsg syscall with its
        neighbors (bounded by the wire_coalesce_* caps)."""
        (syscalls_c, frames_c, bytes_c, fps_hist, queue_gauge) = \
            _send_metrics()
        while True:
            batch: List[_Frame] = []
            iov: List[Any] = []
            nbytes = 0
            with st.cv:
                while st.frames:
                    fr = st.frames[0]
                    if batch and (len(batch) >= self._coalesce_frames
                                  or nbytes + fr.nbytes
                                  > self._coalesce_bytes
                                  or len(iov) + len(fr.segments)
                                  > _IOV_MAX_SEGS):
                        break
                    st.frames.popleft()
                    batch.append(fr)
                    iov.extend(fr.segments)
                    nbytes += fr.nbytes
                if not batch:
                    st.draining = False
                    return
            try:
                if nbytes <= _JOIN_BYTES:
                    # small batches ride one contiguous buffer: copying
                    # a few KiB beats extra iovec entries in the kernel
                    iov = [b"".join(iov)]
                syscalls = self._sendmsg_all(sock, iov)
            except OSError as exc:
                self._fail_send_state(st, exc)
                return
            syscalls_c.add(syscalls)
            frames_c.add(len(batch))
            bytes_c.add(nbytes)
            fps_hist.observe(len(batch) / syscalls)
            with st.cv:
                st.bytes -= nbytes
                queue_gauge.add(-nbytes)
                for fr in batch:
                    fr.done = True
                st.cv.notify_all()
                if not st.frames:
                    st.draining = False
                    return

    @staticmethod
    def _sendmsg_all(sock: socket.socket, iov: List[Any]) -> int:
        """Send the whole iovec list; returns the syscall count. Handles
        partial writes (resume mid-segment via memoryview slicing) and
        chunks at _IOV_MAX_SEGS so the kernel never rejects a batch."""
        iov = list(iov)
        syscalls = 0
        idx = 0
        while idx < len(iov):
            sent = sock.sendmsg(iov[idx:idx + _IOV_MAX_SEGS])
            syscalls += 1
            while idx < len(iov):
                seg_len = len(iov[idx])
                if sent >= seg_len:
                    sent -= seg_len
                    idx += 1
                elif sent:
                    iov[idx] = memoryview(iov[idx])[sent:]
                    break
                else:
                    break
        return max(syscalls, 1)

    def _flush_queues(self, timeout: float = 1.0) -> None:
        """Bounded wait for every outgoing queue to reach the kernel
        (draining any backlog a hold left behind)."""
        deadline = time.monotonic() + timeout
        with self._conn_lock:
            states = list(self._send_states.items())
        for sock, st in states:
            self._release_sends(sock)
            with st.cv:
                st.cv.wait_for(
                    lambda: st.bytes == 0 or st.error is not None,
                    timeout=max(0.0, deadline - time.monotonic()))

    def _hold_sends(self, sock: socket.socket) -> None:
        """Freeze a connection's drain (frames queue but nothing is
        sent) — the deterministic-coalescing seam the forced-coalesce
        tests use; ``_release_sends`` flushes the built-up burst as one
        vectored batch."""
        st = self._state_for(sock)
        with st.cv:
            st.held = True

    def _release_sends(self, sock: socket.socket) -> None:
        st = self._state_for(sock)
        with st.cv:
            st.held = False
            claim = bool(st.frames) and not st.draining \
                and st.error is None
            if claim:
                st.draining = True
            st.cv.notify_all()
        if claim:
            self._drain_pending(sock, st)

    def _socket_for(self, rank: int) -> socket.socket:
        with self._conn_lock:
            sock = self._conns.get(rank)
        if sock is not None:
            return sock
        if not (0 <= rank < len(self._endpoints)):
            log.fatal("net: no endpoint for rank %d", rank)
        host, port = self._endpoints[rank].rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=30)
        # the connect timeout must not linger as an IO timeout: an idle
        # connection's recv loop would otherwise die after 30s of silence
        # and fake a peer loss
        sock.settimeout(None)
        _tune_socket(sock)
        # shm negotiation runs INLINE before the socket becomes visible:
        # either every data frame on this connection rides the ring or
        # none does — no mixed-stream ordering window at switch time
        channel = self._shm_offer(sock) if self._shm_enabled else None
        with self._conn_lock:
            # keep the first established connection per peer
            existing = self._conns.get(rank)
            if existing is not None:
                sock.close()  # the peer's conn-drop reaps its channel side
                if channel is not None:
                    channel.dispose()
                return existing
            self._conns[rank] = sock
            if channel is not None:
                self._shm_channels[sock] = channel
        self._active = True
        # dialed sockets also receive: peers without a listener of their own
        # (remote table clients) get replies back over this connection
        threading.Thread(target=self._recv_loop, args=(sock,), daemon=True,
                         name=f"mvtpu-net-recv-dial-{self.rank}").start()
        if channel is not None:
            threading.Thread(target=self._shm_recv_loop,
                             args=(channel, sock), daemon=True,
                             name=f"mvtpu-shm-recv-dial-{self.rank}").start()
        return sock

    def _accept_loop(self) -> None:
        while self._active:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            _tune_socket(conn)
            with self._conn_lock:
                self._accepted.append(conn)
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True,
                             name=f"mvtpu-net-recv-{self.rank}").start()

    def _read_frame(self, read, srcs_seen: set) -> Optional[Message]:
        """Read ONE v3 frame off a byte stream (``read(n) -> bytes``) —
        the parse shared by the TCP recv loop and the shm ring reader, so
        both transports carry bit-identical framing. Returns None on a
        CRC reject (the length header keeps the stream in sync; the frame
        is discarded and retransmit recovers it); raises
        :class:`_WireDesync` on an unparsable header."""
        head = read(_HEADER.size)
        (magic, version, channel, src, dst, mtype, table_id, msg_id,
         req_id, watermark, deadline_us, nblobs, payload_len,
         crc) = _HEADER.unpack(head)
        # the channel byte's high bit is the trace flag — mask it off
        # before routing (the raw channel's == 1 check must still hold)
        trace = bool(channel & 0x80)
        channel &= 0x7F
        if magic != _MAGIC:
            log.error("net: bad frame magic %x", magic)
            raise _WireDesync("bad frame magic")
        if version != _VERSION:
            log.error("net: wire version %d from peer (want %d)",
                      version, _VERSION)
            raise _WireDesync("wire version mismatch")
        srcs_seen.add(src)
        # the header's payload_len keeps the stream in sync even when the
        # payload is garbage: read it all, checksum, and only then parse
        # blob structure out of it
        payload = read(payload_len) if payload_len else b""
        if zlib.crc32(payload) != crc:
            count("FRAME_CRC_REJECTS")
            log.error("net: CRC mismatch on %s frame from %d — "
                      "frame discarded (retransmit recovers it)",
                      MsgType(mtype), src)
            hop(req_id, "net_crc_reject")
            flight_dump("frame_crc_reject", src=src,
                        msg_type=int(mtype), req_id=req_id)
            return None
        t0 = time.perf_counter()
        off = 0
        blobs = []
        for _ in range(nblobs):
            ndim, dt, nbytes = _BLOB.unpack_from(payload, off)
            off += _BLOB.size
            shape = struct.unpack_from(f"<{ndim}q", payload, off)
            off += 8 * ndim
            dtype = np.dtype(dt.decode().strip())
            blobs.append(np.frombuffer(
                payload, dtype=dtype, count=nbytes // dtype.itemsize,
                offset=off).reshape(shape).copy())
            off += nbytes
        observe("FRAME_DECODE_SECONDS", time.perf_counter() - t0)
        hop(req_id, "net_recv")
        msg = Message(src=src, dst=dst, type=MsgType(mtype),
                      table_id=table_id, msg_id=msg_id,
                      req_id=req_id, watermark=watermark, trace=trace,
                      data=blobs)
        if deadline_us > 0:
            # re-anchor the remaining budget on THIS process's monotonic
            # clock — absolute instants never cross the wire
            msg.deadline = time.monotonic() + deadline_us / 1e6
        msg._wire_channel = channel
        return msg

    def _route(self, msg: Message) -> None:
        """Deliver a received frame to its queue (mailbox / per-peer raw)."""
        if getattr(msg, "_wire_channel", 0) == 1:
            self._raw.setdefault(msg.src, MtQueue()).push(msg)
        else:
            self._mailbox.push(msg)

    def _recv_loop(self, conn: socket.socket) -> None:
        srcs_seen: set = set()
        try:
            while self._active:
                try:
                    msg = self._read_frame(
                        lambda n: _read_exact(conn, n), srcs_seen)
                except _WireDesync:
                    self._drop_conn(conn, srcs_seen)
                    return
                if msg is None:
                    continue  # CRC reject; stream stays in sync
                if msg.type == MsgType.Control_Shm:
                    # transport-internal negotiation: never surfaces to
                    # the mailbox/dispatcher
                    self._shm_serve_accept(conn, msg)
                    continue
                if msg.type == MsgType.Control_Reply_Shm:
                    continue  # stale duplicate; handshake reads inline
                msg._conn = conn  # reply path for listener-less peers
                self._route(msg)
        except (ConnectionError, OSError):
            self._drop_conn(conn, srcs_seen)
            return

    # -- shared-memory transport (runtime/shm.py) ---------------------------
    def _shm_offer(self, sock: socket.socket) -> Optional[ShmChannel]:
        """Inline shm handshake on a fresh dialed connection (nothing else
        is on this wire yet, so a blocking read of the reply is safe).
        Returns the live channel, or None — the caller keeps TCP. The
        segment files are unlinked as soon as the handshake settles: both
        sides hold mappings, so even a kill -9 cannot leak them.
        Negotiation frames bypass the ChaosNet seams deliberately — chaos
        targets data-plane frames; a dropped offer would silently change
        which transport a chaos run exercises."""
        from multiverso_tpu.runtime import shm as shm_mod
        try:
            paths, channel = shm_mod.create_pair(self._shm_bytes)
        except OSError as exc:
            log.error("shm: segment creation failed (%r); staying on TCP",
                      exc)
            return None
        ok = False
        try:
            payload = json.dumps({"c2s": paths[0], "s2c": paths[1]}).encode()
            msg = Message(src=self.rank, dst=-1, type=MsgType.Control_Shm,
                          data=[np.frombuffer(payload, dtype=np.uint8)])
            segments, _ = self._frame_segments(msg, 0)
            sock.settimeout(10.0)
            sock.sendall(b"".join(segments))
            reply = self._read_frame(lambda n: _read_exact(sock, n), set())
            if reply is None or reply.type != MsgType.Control_Reply_Shm:
                log.error("shm: unexpected negotiation reply %s; staying "
                          "on TCP", None if reply is None else reply.type)
                return None
            ans = json.loads(bytes(np.asarray(
                reply.data[0], dtype=np.uint8)).decode()) if reply.data \
                else {}
            if not ans.get("ok"):
                log.info("shm: peer declined (%s); staying on TCP",
                         ans.get("error", "wire_shm off"))
                return None
            ok = True
            return channel
        except (ConnectionError, OSError, ValueError) as exc:
            log.error("shm: negotiation failed (%r); staying on TCP", exc)
            return None
        finally:
            try:
                sock.settimeout(None)
            except OSError:
                pass
            shm_mod.unlink_quiet(*paths)
            if not ok:
                channel.dispose()

    def _shm_serve_accept(self, conn: socket.socket, msg: Message) -> None:
        """Handle a Control_Shm offer: map the pair, start the ring
        reader, accept — or refuse (flag off / unmappable, i.e. a
        non-colocated peer) and the client transparently keeps TCP."""
        from multiverso_tpu.runtime import shm as shm_mod
        channel: Optional[ShmChannel] = None
        error: Optional[str] = None
        if not self._shm_enabled:
            error = "wire_shm is off on this server"
        else:
            try:
                spec = json.loads(bytes(np.asarray(
                    msg.data[0], dtype=np.uint8)).decode())
                channel = shm_mod.open_pair(str(spec["c2s"]),
                                            str(spec["s2c"]))
            except (OSError, ValueError, KeyError, IndexError) as exc:
                error = f"cannot map offered segments: {exc!r}"
        payload: Dict[str, Any] = {"ok": error is None}
        if error is not None:
            payload["error"] = error
            log.info("shm: offer declined: %s", error)
        reply = Message(src=self.rank, dst=msg.src,
                        type=MsgType.Control_Reply_Shm, msg_id=msg.msg_id,
                        data=[np.frombuffer(json.dumps(payload).encode(),
                                            dtype=np.uint8)])
        segments, nbytes = self._frame_segments(reply, 0)
        try:
            # the reply MUST ride TCP — the channel is registered only
            # after the send, or the divert in _enqueue would put the
            # accept on a ring the client is not reading yet. Plain
            # _enqueue: negotiation bypasses the chaos seams like the
            # offer does (they intercept _send/send_via only).
            self._enqueue(conn, segments, nbytes)
        except OSError as exc:
            log.error("shm: accept reply failed: %r", exc)
            if channel is not None:
                channel.dispose()
            return
        if channel is not None:
            with self._conn_lock:
                self._shm_channels[conn] = channel
            threading.Thread(target=self._shm_recv_loop,
                             args=(channel, conn), daemon=True,
                             name=f"mvtpu-shm-recv-{self.rank}").start()
            log.info("shm: transport negotiated (ring %d bytes/dir)",
                     channel.rx.capacity)

    def _shm_recv_loop(self, channel: ShmChannel,
                       conn: socket.socket) -> None:
        """Ring-side twin of ``_recv_loop``: same framing, same routing;
        replies to ring-arrived frames address the CHANNEL (``msg._conn``),
        so they ride the ring back. The reader owns the mappings' final
        release — it is the last thread touching them."""
        from multiverso_tpu.runtime.shm import _shm_metrics
        rx_frames = _shm_metrics()[2]
        srcs_seen: set = set()
        try:
            while self._active:
                try:
                    msg = self._read_frame(channel.read_exact, srcs_seen)
                except _WireDesync:
                    # garbage on the ring: kill the whole connection (TCP
                    # included) — the reconnect path renegotiates
                    self._drop_conn(conn, srcs_seen)
                    break
                if msg is None:
                    continue  # CRC reject; stream stays in sync
                rx_frames.add(1)
                msg._conn = channel
                self._route(msg)
        except (ConnectionError, OSError):
            if self._active and not channel.closed:
                # the PEER killed the ring (its finalize flipped the
                # shared flags) while our TCP side may sit in a blocked
                # recv that a dead socket cannot always interrupt: run
                # the same conn-drop path a TCP EOF would — pops the
                # socket AND the channel, pushes the peer-lost sentinels
                # that wake blocked waiters into recovery
                self._drop_conn(conn, srcs_seen)
        finally:
            channel.close()
            channel.dispose()

    def _drop_conn(self, conn: socket.socket, srcs_seen: set) -> None:
        """A connection died: prune its bookkeeping and — if the transport
        is still live — push a peer-lost sentinel so blocked receivers
        (mid-allreduce, pending table replies) fail fast instead of hanging
        until finalize(). Only the dead peer's raw queues are poisoned."""
        with self._conn_lock:
            state = self._send_states.pop(conn, None)
            channel = self._shm_channels.pop(conn, None)
            if conn in self._accepted:
                self._accepted.remove(conn)
            for rank, sock in list(self._conns.items()):
                if sock is conn:
                    del self._conns[rank]
                    srcs_seen = srcs_seen | {rank}
        if channel is not None:
            # the TCP liveness channel died: fail ring waiters fast (its
            # reader thread disposes the mappings on exit)
            channel.close()
        if state is not None:
            # fail queued frames + wake flush/backpressure waiters; the
            # drain thread exits on the error mark
            err = ConnectionError("net: peer connection lost")
            with state.cv:
                if state.error is None:
                    state.error = err
                for fr in state.frames:
                    fr.error = err
                state.frames.clear()
                gauge_add("SEND_QUEUE_BYTES", -state.bytes)
                state.bytes = 0
                state.cv.notify_all()
        try:
            conn.close()
        except OSError:
            pass
        if not self._active:
            return  # normal shutdown; finalize() exits the queues
        sentinel = Message(src=-1, dst=self.rank, type=MsgType.Reply_Error)
        sentinel._conn = conn
        self._mailbox.push(sentinel)
        for src in srcs_seen:
            q = self._raw.get(src)
            if q is not None:
                q.push(sentinel)


class AllreduceEngine:
    """Host collectives over the raw channel (reference AllreduceEngine
    capability). On-mesh the algorithm choice (Bruck allgather /
    recursive-halving reduce-scatter) belongs to XLA; here a ring
    reduce-scatter + ring allgather covers the host path, which is
    latency-dominated at external-client scales."""

    def __init__(self, net: TcpNet) -> None:
        self.net = net

    def allreduce(self, data: np.ndarray) -> np.ndarray:
        """Elementwise sum across all ranks; every rank gets the result."""
        n, r = self.net.size, self.net.rank
        if n <= 1:
            return np.asarray(data).copy()
        flat = np.asarray(data).reshape(-1)
        pad = (-flat.size) % n
        work = np.concatenate([flat, np.zeros(pad, flat.dtype)])
        chunks = np.split(work.copy(), n)
        right = (r + 1) % n
        left = (r - 1) % n
        # ring reduce-scatter: after n-1 steps chunk (r+1)%n is fully reduced
        for step in range(n - 1):
            send_idx = (r - step) % n
            recv_idx = (r - step - 1) % n
            got = self.net.send_recv(right, [chunks[send_idx]], left)
            if got is None:
                log.fatal("allreduce: transport shut down mid-collective")
            chunks[recv_idx] = chunks[recv_idx] + got[0]
        # ring allgather of the reduced chunks
        for step in range(n - 1):
            send_idx = (r - step + 1) % n
            recv_idx = (r - step) % n
            got = self.net.send_recv(right, [chunks[send_idx]], left)
            if got is None:
                log.fatal("allreduce: transport shut down mid-collective")
            chunks[recv_idx] = got[0]
        out = np.concatenate(chunks)
        if pad:
            out = out[:flat.size]
        return out.reshape(np.asarray(data).shape)

    def allgather(self, data: np.ndarray) -> List[np.ndarray]:
        """Every rank's array, in rank order (reference Allgather parity)."""
        n, r = self.net.size, self.net.rank
        parts: List[Optional[np.ndarray]] = [None] * n
        parts[r] = np.asarray(data).copy()
        right = (r + 1) % n
        left = (r - 1) % n
        for step in range(n - 1):
            send_idx = (r - step) % n
            got = self.net.send_recv(right, [parts[send_idx]], left)
            if got is None:
                log.fatal("allgather: transport shut down mid-collective")
            parts[(r - step - 1) % n] = got[0]
        return parts  # type: ignore[return-value]
