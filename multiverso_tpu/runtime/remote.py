"""Remote table serving — the cross-process parameter-server path.

Reference capability (not copied): a worker in ANY process reaches tables via
worker actor → Communicator → network → Server actor, with the reply
retracing the path (``src/worker.cpp:30-76``, ``src/communicator.cpp:69-105``,
``src/server.cpp:36-58``); external hosts registered through the Controller
(``src/controller.cpp:38-80``).

TPU-era design: ONE process owns the device mesh and runs the dispatcher
(:mod:`multiverso_tpu.runtime.server`); any other process is an off-mesh
client. :class:`RemoteServer` is the net↔dispatcher bridge — a pump thread
pops table-request frames from the TCP mailbox, decodes them into the same
request structures local workers enqueue, and attaches a completion that
frames the reply back over the socket the request arrived on (clients never
bind a listener). :class:`RemoteClient` registers (gets a worker id + the
table directory), then hands out worker-table proxies that share ALL client
shaping code with the in-process workers — only the channel differs — so the
BSP clocks, per-worker updater state, and option envelopes behave
identically across the wire.

Fault story (:mod:`multiverso_tpu.fault`, Li et al. OSDI'14's replayable
idempotent messages): every correlated request carries a session-unique
``req_id``; the server keeps a bounded dedup window mapping req_id to the
cached reply, so a client may retransmit freely — on a reply timeout
(drops, duplicated frames) or after reconnect-and-resume (connection loss,
server restart) — and a retried Add is applied exactly once. Remote
workers renew a lease with heartbeats; the sync watchdog evicts expired
leases from the BSP/SSP clock gates (:mod:`multiverso_tpu.fault.detector`).
Transports are built through :func:`multiverso_tpu.fault.inject.make_net`,
so the whole path runs under seeded fault injection via config flags.

Payloads ride the :mod:`multiverso_tpu.runtime.wire` codec; float32 arrays
are SparseFilter-compressed when the ``wire_compression`` flag is on and the
sparse form is smaller (the reference applied SparseFilter on exactly these
host hops, ``src/table/sparse_matrix_table.cpp:147-153``).
"""

from __future__ import annotations

import itertools
import os
import random
import signal
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu import io as mv_io
from multiverso_tpu.dashboard import Dashboard, count, gauge_set, observe
from multiverso_tpu.fault.detector import LivenessDetector
from multiverso_tpu.fault.inject import make_net
from multiverso_tpu.fault.retry import (CircuitBreaker, RetryBudget,
                                        RetryPolicy)
from multiverso_tpu.obs.metrics import StatsSnapshot
from multiverso_tpu.obs.trace import flight_dump, hop, tag_tenant
from multiverso_tpu.runtime.admission import resolve_tenant
from multiverso_tpu.runtime.contracts import slot_free
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id
from multiverso_tpu.runtime.net import TcpNet
from multiverso_tpu.runtime import wire
from multiverso_tpu.tables.array_table import ArrayWorker
from multiverso_tpu.tables.base import Completion, WorkerTable
from multiverso_tpu.tables.kv_table import KVWorker
from multiverso_tpu.tables.matrix_table import MatrixWorker
from multiverso_tpu.tables.sparse_table import SparseWorker

# wire_quant_bits lives in config.py (must exist before this module is
# first imported so mv.init(wire_quant_bits=...) works)
config.define_bool("wire_compression", True,
                   "SparseFilter-compress float32 payloads on host hops "
                   "when the sparse form is smaller")


# -- server side -------------------------------------------------------------

# dedup-window sentinel: the request arrived and is being processed; a
# replay seen now is swallowed (the original's completion will reply)
_INFLIGHT = object()


class WrongShardError(Exception):
    """A Reply_WrongShard came back: the request was stamped with a layout
    version older than the serving shard's installed layout, so it was
    REFUSED before applying. Carries the server's layout version and the
    new manifest so the shard router re-fetches and re-routes without an
    extra Control_Layout round trip."""

    def __init__(self, layout_version: int, manifest) -> None:
        super().__init__(f"stale shard layout (server at version "
                         f"{layout_version})")
        self.layout_version = int(layout_version)
        self.manifest = manifest


class _NetCompletion:
    """Dispatcher completion that frames the result back over the wire and
    records it in the server's dedup window, so a replay of the same
    request re-sends this reply instead of re-applying the request."""

    __slots__ = ("_server", "_conn", "_template", "_compress")

    def __init__(self, server: "RemoteServer", conn, template: Message,
                 compress: bool) -> None:
        self._server = server
        self._conn = conn
        self._template = template
        self._compress = compress

    def _reply(self, msg_type: MsgType, payload: Any) -> None:
        t = self._template
        msg = Message(src=t.dst, dst=t.src, type=msg_type,
                      table_id=t.table_id, msg_id=t.msg_id, req_id=t.req_id,
                      watermark=self._server.append_watermark(),
                      data=wire.encode(payload, compress=self._compress))
        self._server._dedup_store(t.req_id, msg)
        hop(t.req_id, "reply_sent")
        try:
            self._server._net.send_via(self._conn, msg)
        except OSError as exc:
            log.error("remote: reply to worker %d failed: %r (the client "
                      "recovers it via retransmit + the dedup cache)",
                      t.src, exc)

    def done(self, result: Any) -> None:
        reply_type = (MsgType.Reply_Get
                      if self._template.type == MsgType.Request_Get
                      else MsgType.Reply_Add)
        self._reply(reply_type, result)

    def fail(self, error: BaseException) -> None:
        # admission refusals and deadline drops ship their exact truthful
        # string (clients key graceful degradation on the "shed: " /
        # "deadline_exceeded" prefixes); everything else ships its repr
        self._reply(MsgType.Reply_Error,
                    getattr(error, "wire_text", None) or repr(error))


class _ReadCompletion:
    """Completion for a slot-free Request_Read: replies Reply_Read stamped
    with the primary's append watermark. No dedup entry — reads are
    idempotent, a replayed read just re-serves."""

    __slots__ = ("_server", "_conn", "_template", "_compress")

    def __init__(self, server: "RemoteServer", conn, template: Message,
                 compress: bool) -> None:
        self._server = server
        self._conn = conn
        self._template = template
        self._compress = compress

    def _reply(self, msg_type: MsgType, payload: Any) -> None:
        t = self._template
        msg = Message(src=t.dst, dst=t.src, type=msg_type,
                      table_id=t.table_id, msg_id=t.msg_id, req_id=t.req_id,
                      watermark=self._server.append_watermark(),
                      data=wire.encode(payload, compress=self._compress))
        hop(t.req_id, "read_reply_sent")
        try:
            self._server._net.send_via(self._conn, msg)
        except OSError as exc:
            log.error("remote: read reply failed: %r (the client falls "
                      "back to another endpoint)", exc)

    def done(self, result: Any) -> None:
        count("READS_SERVED_PRIMARY")
        self._reply(MsgType.Reply_Read, result)

    def fail(self, error: BaseException) -> None:
        self._reply(MsgType.Reply_Error,
                    getattr(error, "wire_text", None) or repr(error))


class _QueryCompletion(_ReadCompletion):
    """Completion for a slot-free Request_Query on the primary: replies
    Reply_Query stamped with the append watermark. Idempotent like a
    read — no dedup entry; a replayed query just re-scores. The done
    counter is the query plane's zero-primary-dispatch proof
    (BENCH_r13 mirrors BENCH_r07's read-tier bar on it)."""

    __slots__ = ()

    def done(self, result: Any) -> None:
        count("QUERIES_SERVED_PRIMARY")
        self._reply(MsgType.Reply_Query, result)


class RemoteServer:
    """Serves this process's tables to off-mesh clients over TCP."""

    def __init__(self, zoo) -> None:
        self._zoo = zoo
        self._net = make_net()  # ChaosNet under fault_spec, else TcpNet
        self._thread: Optional[threading.Thread] = None
        self._wid_lock = threading.Lock()
        self._next_remote = 0
        self._free_slots: List[int] = []  # recycled by Control_Deregister
        # slot -> the connection that registered it: a deregister is honored
        # only from that connection, so a replayed/forged deregister cannot
        # free a slot that was re-leased to a different client
        self._leased: Dict[int, Any] = {}
        # client session nonce -> worker id: the authority for
        # reconnect-and-resume (a client proves slot ownership with the
        # session it registered under, not with its — dead — connection)
        self._sessions: Dict[int, int] = {}
        # bounded idempotent-replay window: req_id -> _INFLIGHT | cached
        # reply Message (re-sent verbatim over the replaying frame's conn)
        self._dedup: "OrderedDict[int, Any]" = OrderedDict()
        self._dedup_lock = threading.Lock()
        self._dedup_max = max(16, int(config.get_flag("dedup_window")))
        # warm-standby replication subscribers (durable/standby.py):
        # connections that receive every WAL record + periodic heartbeats
        self._standbys: List[Any] = []
        self._standby_lock = threading.Lock()
        self._standby_hb: Optional[threading.Thread] = None
        self._standby_hb_stop = threading.Event()
        self.liveness = LivenessDetector(
            float(config.get_flag("lease_seconds")))
        self.endpoint: Optional[str] = None
        # shard-group membership (shard/group.py): the layout manifest
        # this member serves over Control_Layout — either the dict
        # itself, or a path loaded lazily (the group publishes the file
        # only after every member has bound its endpoint)
        self.layout: Optional[Dict[str, Any]] = None
        self.layout_path: str = ""
        # live-migration layout fencing (shard/reshard.py): requests
        # stamped with a layout version below this are refused with
        # Reply_WrongShard instead of applied — the router re-fetches and
        # re-routes. 0 = no fencing (unsharded servers, pre-migration
        # groups); bumped only by a Control_Migrate_Cutover install.
        self.layout_version: int = 0

    def append_watermark(self) -> int:
        """The primary's WAL append sequence (-1 when serving without
        durability — no staleness unit exists then). Reads a plain int
        written on the dispatcher thread; safe from any thread."""
        server = self._zoo.server
        wal = server.wal if server is not None else None
        return int(wal.seq) if wal is not None else -1

    def serve(self, endpoint: str = "127.0.0.1:0") -> str:
        """Bind + start the pump; returns the dialable endpoint."""
        self.endpoint = self._net.bind(0, endpoint)
        if self._zoo.server is not None:
            # the sync watchdog polls this to escalate stalls to evictions
            self._zoo.server.liveness = self.liveness
            if self._zoo.server.wal is not None:
                # replication fan-out: every durable append reaches the
                # subscribed standbys over their replication connections
                self._zoo.server.wal.add_observer(self._replicate_record)
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="mv-remote-serve")
        self._thread.start()
        return self.endpoint

    def stop(self) -> None:
        if (self._zoo.server is not None
                and self._zoo.server.liveness is self.liveness):
            self._zoo.server.liveness = None
        self._standby_hb_stop.set()
        if self._standby_hb is not None:
            self._standby_hb.join(timeout=10)
            self._standby_hb = None
        self._net.finalize()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- idempotent replay ---------------------------------------------------
    def _replayed(self, msg: Message) -> bool:
        """True → this frame replays an already-seen request: re-send the
        cached reply (if built) over THIS frame's connection — the original
        may have gone to a connection that no longer exists — or swallow
        the duplicate while the original is still in flight."""
        if msg.req_id == 0:
            return False
        with self._dedup_lock:
            hit = self._dedup.get(msg.req_id)
            if hit is None:
                self._dedup[msg.req_id] = _INFLIGHT
                while len(self._dedup) > self._dedup_max:
                    self._dedup.popitem(last=False)
                gauge_set("SERVER_DEDUP_OCCUPANCY", len(self._dedup))
                return False
        count("SERVER_DEDUP_HITS")
        hop(msg.req_id, "server_dedup_hit")
        if hit is not _INFLIGHT:
            try:
                self._net.send_via(msg._conn, hit)
            except OSError as exc:
                log.error("remote: dedup re-reply failed: %r", exc)
        return True

    def _dedup_store(self, req_id: int, reply: Message) -> None:
        if req_id == 0:
            return
        with self._dedup_lock:
            if req_id in self._dedup:
                self._dedup[req_id] = reply

    def seed_dedup(self, seeds) -> None:
        """Rebuild the idempotent-replay window from recovered/replicated
        WAL records — ``(req_id, worker, msg_id)`` triples in replay
        order. A client retransmitting an Add that was logged before the
        crash/failover gets a synthesized ACK instead of a second apply:
        exactly-once survives the restart. Remote Add replies are
        ACK-shaped (the client ignores the payload), so the synthesis is
        faithful to what the dead server would have re-sent."""
        with self._dedup_lock:
            for req_id, worker, msg_id in list(seeds)[-self._dedup_max:]:
                self._dedup[int(req_id)] = Message(
                    src=0, dst=int(worker), type=MsgType.Reply_Add,
                    msg_id=int(msg_id), req_id=int(req_id),
                    data=wire.encode(None))
            while len(self._dedup) > self._dedup_max:
                self._dedup.popitem(last=False)

    # -- warm-standby replication (durable/standby.py) -----------------------
    def _replicate_record(self, seq: int, req_id: int, worker: int,
                          table_id: int, msg_id: int, blobs) -> None:
        """WAL observer: forward one durable record to every subscribed
        standby. Runs on the dispatcher thread right after the append, so
        a record the primary ACKs was already written to each standby's
        socket before the ACK frame — the kernel delivers it even if the
        primary dies the next instant. Each record carries its append
        sequence so replicas track their replay watermark and DETECT
        stream gaps (a missing sequence forces a resubscribe)."""
        with self._standby_lock:
            conns = list(self._standbys)
        for conn in conns:
            msg = Message(src=worker, dst=-1,
                          type=MsgType.Control_Wal_Record,
                          table_id=table_id, msg_id=msg_id, req_id=req_id,
                          watermark=seq, data=list(blobs))
            try:
                # flush: the record must reach the standby's socket before
                # the client's ACK is even queued — with the coalescing
                # send queues the two frames ride different connections,
                # so the dispatcher-thread ordering alone no longer
                # implies kernel-delivery ordering
                self._net.send_via(conn, msg, flush=True)
            except OSError as exc:
                log.error("remote: replication to a standby failed (%r); "
                          "dropping the subscriber — it will resubscribe "
                          "with a full state transfer", exc)
                with self._standby_lock:
                    if conn in self._standbys:
                        self._standbys.remove(conn)

    def _subscribe_standby(self, msg: Message) -> None:
        """Handle Control_Replicate: quiesced full-state transfer (every
        table + the Add half of the dedup window), then subscribe the
        connection to the live record stream. The snapshot and the
        subscription happen in ONE dispatcher-serialized block, so no add
        can fall between them."""
        wal = self._zoo.server.wal
        if wal is None:
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Reply_Error,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode("replication needs durability: start the "
                                 "primary with the wal_dir flag")))
            return

        def transfer():
            tables = {}
            for table_id, table in list(self._zoo.server._tables.items()):
                stream = mv_io.MemoryStream()
                table.store(stream)
                tables[int(table_id)] = np.frombuffer(
                    stream.getvalue(), dtype=np.uint8)
            with self._dedup_lock:
                dedup = [[m.req_id, m.dst, m.msg_id]
                         for m in self._dedup.values()
                         if isinstance(m, Message)
                         and m.type == MsgType.Reply_Add]
            with self._standby_lock:
                # idempotent: a gap-triggered resubscribe arrives over the
                # SAME live connection — double-adding it would double
                # every later record
                if msg._conn not in self._standbys:
                    self._standbys.append(msg._conn)
            # the snapshot's watermark, read inside the serialized block:
            # every record the standby will see next has seq > this
            return tables, dedup, int(wal.seq)

        tables, dedup, watermark = self._zoo.server.run_serialized(transfer)
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Replicate,
            msg_id=msg.msg_id, req_id=msg.req_id, watermark=watermark,
            data=wire.encode({"tables": tables, "dedup": dedup,
                              "watermark": watermark})))
        log.info("remote: standby subscribed (%d table(s), %d dedup "
                 "seed(s) transferred)", len(tables), len(dedup))
        self._ensure_standby_heartbeats()

    # -- live key-range migration (shard/reshard.py) -------------------------
    def _subscribe_migrate(self, msg: Message) -> None:
        """Handle Control_Migrate: a joining shard asks for a quiesced
        raw-value transfer of specific shard-local id ranges, then tails
        this donor's WAL record stream like a standby (the subscriber
        filters to its ranges; the donor fan-out stays one code path).
        Snapshot + subscription happen in ONE dispatcher-serialized block
        — no Add falls between the extracted values and the first tailed
        record, the same zero-loss argument the standby transfer makes."""
        wal = self._zoo.server.wal
        if wal is None:
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Reply_Error,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode("live migration needs durability: start "
                                 "the donor with the wal_dir flag")))
            return
        ranges = wire.decode(msg.data).get("tables", {})

        def transfer():
            tables = {}
            for table_id, (lo, hi) in ranges.items():
                table = self._zoo.server._tables[int(table_id)]
                tables[int(table_id)] = table.extract_range(int(lo),
                                                            int(hi))
            with self._standby_lock:
                if msg._conn not in self._standbys:
                    self._standbys.append(msg._conn)
            return tables, int(wal.seq)

        tables, watermark = self._zoo.server.run_serialized(transfer)
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Migrate,
            msg_id=msg.msg_id, req_id=msg.req_id, watermark=watermark,
            data=wire.encode({"tables": tables, "watermark": watermark})))
        log.info("remote: migration subscriber attached (%d range(s), "
                 "watermark %d)", len(tables), watermark)
        self._ensure_standby_heartbeats()

    def _migrate_cutover(self, msg: Message) -> None:
        """Handle Control_Migrate_Cutover: install the attached manifest
        (the layout-version fence goes up) and answer with the WAL seq
        after a dispatcher drain. Ordering is the whole correctness
        argument: this handler runs on the pump thread — the ONLY thread
        that enqueues wire requests — so once the fence is set here, no
        further stale-stamped Add can enter the dispatcher; the
        run_serialized barrier then drains everything already queued, so
        every acknowledged Add on this donor has seq <= the returned
        watermark and the record stream is silent above it. Also the
        rollback vehicle: aborting re-installs the old topology under a
        HIGHER version through the same RPC."""
        payload = wire.decode(msg.data)
        manifest = payload["manifest"]
        version = int(manifest.get("layout_version", 1))
        if version > self.layout_version:
            self.layout = manifest
            self.layout_version = version
        server = self._zoo.server
        if server is not None and server.wal is not None:
            watermark = server.run_serialized(lambda: int(server.wal.seq))
        else:
            watermark = -1
        count("MIGRATION_CUTOVERS")
        hop(msg.req_id, "migrate_cutover")
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Migrate_Cutover,
            msg_id=msg.msg_id, req_id=msg.req_id, watermark=watermark,
            data=wire.encode({"watermark": watermark,
                              "layout_version": self.layout_version})))
        log.info("remote: cutover to layout version %d at watermark %d",
                 version, watermark)

    def _ensure_standby_heartbeats(self) -> None:
        """Primary→standby heartbeats: the standby's lease on the primary
        must stay renewed while the WAL idles, or a quiet training lull
        would look like primary death."""
        if self._standby_hb is not None:
            return
        period = float(config.get_flag("heartbeat_seconds"))
        if period <= 0:
            return
        self._standby_hb = threading.Thread(
            target=self._standby_heartbeat_loop, args=(period,),
            daemon=True, name="mv-remote-standby-hb")
        self._standby_hb.start()

    def _standby_heartbeat_loop(self, period: float) -> None:
        while not self._standby_hb_stop.wait(period):
            try:
                # a fresh frame per beat: the watermark stamp keeps the
                # replicas' view of the primary's append position current
                # while the WAL idles — the lag a replica admits reads
                # against stays honest
                beat = Message(src=0, dst=-1,
                               type=MsgType.Control_Heartbeat,
                               watermark=self.append_watermark())
                with self._standby_lock:
                    conns = list(self._standbys)
                for conn in conns:
                    try:
                        self._net.send_via(conn, beat)
                    except OSError:
                        with self._standby_lock:
                            if conn in self._standbys:
                                self._standbys.remove(conn)
            except Exception as exc:  # noqa: BLE001 — a dead heartbeat
                # thread starves every standby's lease into a FALSE
                # failover; log and keep beating
                log.error("remote: standby heartbeat tick failed: %r", exc)

    # -- pump ---------------------------------------------------------------
    def _pump(self) -> None:
        compress = bool(config.get_flag("wire_compression"))
        while True:
            try:
                msg = self._net.recv()
            except ConnectionError:
                continue  # a client connection died; its waiters are remote
            if msg is None:
                return
            try:
                self._handle(msg, compress)
            except Exception as exc:  # noqa: BLE001 — keep serving
                log.error("remote server: error on %s: %r", msg.type, exc)
                _NetCompletion(self, msg._conn, msg, False).fail(exc)

    def _handle(self, msg: Message, compress: bool) -> None:
        if msg.src >= 0:
            # ANY frame from a worker renews its lease; dedicated
            # heartbeats only matter while the client idles or blocks
            self.liveness.beat(msg.src)
        hop(msg.req_id, "server_recv")
        if msg.type == MsgType.Control_Heartbeat:
            return
        if msg.type == MsgType.Control_Stats:
            self._reply_stats(msg)
            return
        if msg.type == MsgType.Control_Layout:
            self._reply_layout(msg)
            return
        if msg.type == MsgType.Control_Watermark:
            self._reply_watermark(msg)
            return
        if msg.type == MsgType.Control_Traces:
            self._reply_traces(msg)
            return
        if msg.type == MsgType.Control_Profile:
            self._reply_profile(msg)
            return
        if msg.type == MsgType.Control_Digest:
            self._reply_digest(msg)
            return
        if msg.type == MsgType.Control_Cut:
            self._handle_cut(msg)
            return
        if msg.type == MsgType.Request_Read:
            self._serve_read(msg, compress)
            return
        if msg.type == MsgType.Request_Query:
            self._serve_query(msg, compress)
            return
        if msg.type == MsgType.Control_Register:
            if not self._replayed(msg):
                self._register_client(msg)
            return
        if msg.type == MsgType.Control_Deregister:
            self._deregister_client(msg)
            return
        if msg.type == MsgType.Control_Replicate:
            self._subscribe_standby(msg)
            return
        if msg.type == MsgType.Control_Migrate:
            self._subscribe_migrate(msg)
            return
        if msg.type == MsgType.Control_Migrate_Cutover:
            self._migrate_cutover(msg)
            return
        if msg.type == MsgType.Server_Finish_Train:
            self._zoo.server.send(Message(
                src=msg.src, dst=-1, type=msg.type, table_id=msg.table_id,
                msg_id=msg.msg_id))
            return
        if msg.type not in (MsgType.Request_Get, MsgType.Request_Add):
            log.error("remote server: unhandled frame type %s", msg.type)
            return
        if self._replayed(msg):
            return
        if (self.layout_version > 0 and msg.req_id
                and 0 <= msg.watermark < self.layout_version):
            # Stale-layout fence, strictly AFTER the dedup check: a
            # replayed-but-already-applied Add re-serves its cached ACK
            # above and never lands here, so a WrongShard refusal
            # GUARANTEES the request did not apply on this shard — the
            # router may safely re-issue it under a fresh req_id. Pop the
            # _INFLIGHT entry _replayed just inserted: this req_id's
            # story on this shard is over.
            with self._dedup_lock:
                if self._dedup.get(msg.req_id) is _INFLIGHT:
                    del self._dedup[msg.req_id]
            count("MIGRATION_WRONG_SHARD_REPLIES")
            hop(msg.req_id, "wrong_shard_refused")
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Reply_WrongShard,
                table_id=msg.table_id, msg_id=msg.msg_id, req_id=msg.req_id,
                trace=msg.trace,
                data=wire.encode({"layout_version": self.layout_version,
                                  "manifest": self.layout})))
            return
        request = wire.decode(msg.data)
        completion = _NetCompletion(self, msg._conn, msg, compress)
        # req_id rides into the dispatcher so server-side stages (gate
        # defer/release, WAL append, apply) land on the request's trace
        forward = Message(
            src=msg.src, dst=-1, type=msg.type, table_id=msg.table_id,
            msg_id=msg.msg_id, req_id=msg.req_id, deadline=msg.deadline,
            data=[request, completion])
        if (msg.type == MsgType.Request_Add and msg.req_id
                and self._zoo.server.wal is not None):
            # raw wire blobs ride along for the dispatcher's write-ahead
            # append (Server._wal_append) — logged before apply/ACK,
            # replayed through wire.decode at recovery
            forward._wal = (msg.req_id, msg.src, msg.table_id, msg.msg_id,
                            msg.data)
        hop(msg.req_id, "dispatch_enqueue")
        self._zoo.server.send(forward)

    @slot_free
    def _serve_read(self, msg: Message, compress: bool) -> None:
        """Request_Read on the PRIMARY: a slot-free Get — no worker slot,
        no lease, no dedup entry. The request rides the dispatcher queue
        as an administrative Get (src=-1 bypasses every round gate), so
        it serializes with applies, and the Reply_Read is stamped with the
        append watermark at reply time. The primary is trivially "fresh",
        so the request's staleness budget is always satisfied here — this
        is the fallback target when no replica qualifies."""
        request = wire.decode(msg.data)
        completion = _ReadCompletion(self, msg._conn, msg, compress)
        hop(msg.req_id, "dispatch_enqueue")
        self._zoo.server.send(Message(
            src=-1, dst=-1, type=MsgType.Request_Get,
            table_id=msg.table_id, msg_id=msg.msg_id, req_id=msg.req_id,
            deadline=msg.deadline,
            data=[request, completion]))

    @slot_free
    def _serve_query(self, msg: Message, compress: bool) -> None:
        """Request_Query on the PRIMARY: slot-free like a Request_Read —
        no worker slot, no lease, no dedup entry. Rides the dispatcher
        queue under its own type (src=-1, serving lane, never clocked)
        so the top-k scoring serializes with applies, and the
        Reply_Query is stamped with the append watermark at reply
        time. The fallback target when no replica admits the query's
        staleness budget."""
        request = wire.decode(msg.data)
        completion = _QueryCompletion(self, msg._conn, msg, compress)
        hop(msg.req_id, "dispatch_enqueue")
        self._zoo.server.send(Message(
            src=-1, dst=-1, type=MsgType.Request_Query,
            table_id=msg.table_id, msg_id=msg.msg_id, req_id=msg.req_id,
            deadline=msg.deadline,
            data=[request, completion]))

    @slot_free
    def _reply_watermark(self, msg: Message) -> None:
        """Control_Watermark: this process's position in the WAL stream —
        slot-free like the stats probe (an operator asking 'how stale is
        this endpoint' must get an answer even when every slot is
        taken). A traced replica-served Get fires one of these at the
        primary under its own req_id (the read tier's confirm leg), so
        the reply-sent hop below is the 'primary watermark path' segment
        of a stitched cross-process trace."""
        watermark = self.append_watermark()
        hop(msg.req_id, "watermark_reply_sent")
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Watermark,
            msg_id=msg.msg_id, req_id=msg.req_id, watermark=watermark,
            trace=msg.trace,
            data=wire.encode({"role": "primary", "watermark": watermark,
                              "primary_watermark": watermark, "lag": 0})))

    @slot_free
    def _reply_traces(self, msg: Message) -> None:
        """Control_Traces: ship this process's recent per-request traces
        plus its wall clock at reply time — the pull half of fleet trace
        stitching (obs/collector.py). Slot-free like the stats probe."""
        from multiverso_tpu.obs.trace import TRACES
        n = max(1, int(config.get_flag("trace_export_max")))
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Traces,
            msg_id=msg.msg_id, req_id=msg.req_id,
            data=wire.encode({"role": "primary",
                              "endpoint": self.endpoint or "",
                              "t_reply_ns": time.time_ns(),
                              "traces": TRACES.export(n),
                              # tenant tags ride as a sibling key legacy
                              # collectors simply ignore (and legacy
                              # senders omit — frames are unchanged)
                              "tenants": TRACES.export_tenants(n)})))

    @slot_free
    def _reply_profile(self, msg: Message) -> None:
        """Control_Profile: ship this process's sampling-profiler report
        (per-thread self-time, wait-site seconds, top collapsed stacks)
        — the pull half of fleet attribution (obs/critpath.py).
        Slot-free like the stats probe: a profile of a wedged server is
        worth the most exactly when every slot is taken."""
        from multiverso_tpu.obs.profiler import PROFILER
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Profile,
            msg_id=msg.msg_id, req_id=msg.req_id,
            data=wire.encode({"role": "primary",
                              "endpoint": self.endpoint or "",
                              "t_reply_ns": time.time_ns(),
                              "profile": PROFILER.report()})))

    @slot_free
    def _reply_digest(self, msg: Message) -> None:
        """Control_Digest: per-table order-independent content digests at
        this primary's EXACT append watermark — digest and fence are read
        in one dispatcher-serialized block, so no Add can land between
        them. Slot-free like the stats probe: auditing a wedged or
        diverged server is exactly when every slot is taken."""
        from multiverso_tpu.obs.audit import digest_payload
        server = self._zoo.server
        t0 = time.perf_counter()

        def run():
            wal = server.wal
            return digest_payload(
                server._tables, role="primary", endpoint=self.endpoint or "",
                watermark=int(wal.seq) if wal is not None else -1,
                layout_version=self.layout_version)

        payload = server.run_serialized(run, timeout=None)
        observe("AUDIT_DIGEST_SECONDS", time.perf_counter() - t0)
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Digest,
            msg_id=msg.msg_id, req_id=msg.req_id,
            watermark=int(payload.get("watermark", -1)),
            data=wire.encode(payload)))

    @slot_free
    def _handle_cut(self, msg: Message) -> None:
        """Control_Cut: snapshot every table at this shard's WAL fence
        (durable/cut.py) and reply the fence + digests. Runs on the pump
        thread — the only thread that enqueues wire requests — so the
        dispatcher-serialized capture block drains everything already
        accepted and fences out everything after, the same quiesce shape
        as the Control_Replicate transfer. A durability-less server
        refuses: without a WAL there is no fence to cut at."""
        from multiverso_tpu.durable import cut as cut_mod
        if self._zoo.server.wal is None:
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Reply_Error,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode("consistent cuts need durability: start "
                                 "the server with the wal_dir flag")))
            return
        request = wire.decode(msg.data) if msg.data else {}
        request = request if isinstance(request, dict) else {}
        reply = cut_mod.capture_cut(self, str(request.get("cut_id", "adhoc")))
        if request.get("kill") == "shard":
            # chaos drill (MV_CUT_KILL=shard): die AFTER the local
            # snapshot but BEFORE replying — the coordinator sees a
            # timeout, the cut fails, and the previous manifest must
            # remain the fleet's recovery point
            log.error("cut: MV_CUT_KILL=shard — dying before the cut "
                      "reply (drill)")
            os.kill(os.getpid(), signal.SIGKILL)
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Cut,
            msg_id=msg.msg_id, req_id=msg.req_id,
            watermark=int(reply["fence"]), data=wire.encode(reply)))

    @slot_free
    def _reply_stats(self, msg: Message) -> None:
        """Control_Stats: ship this process's full dashboard — monitors,
        counters, gauges, histograms as bucket arrays — back over the
        probing connection. No worker slot, no lease, no dedup entry: a
        stats probe must stay readable even when every slot is taken or
        the clock gates are wedged (that is when an operator needs it)."""
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Stats,
            msg_id=msg.msg_id, req_id=msg.req_id,
            data=wire.encode(Dashboard.snapshot())))

    @slot_free
    def _reply_layout(self, msg: Message) -> None:
        """Control_Layout: ship the shard group's layout manifest. Like
        the stats probe: no worker slot, no lease, no dedup entry — a
        bootstrapping client must be able to ask ANY member."""
        layout = self.layout
        if layout is None and self.layout_path:
            try:
                import json
                with open(self.layout_path, "r", encoding="utf-8") as f:
                    layout = self.layout = json.load(f)
            except (OSError, ValueError):
                layout = None  # manifest not published yet — reply error
        if layout is None:
            self._net.send_via(msg._conn, Message(
                src=0, dst=msg.src, type=MsgType.Reply_Error,
                msg_id=msg.msg_id, req_id=msg.req_id,
                data=wire.encode("no shard layout: this server is not a "
                                 "shard-group member (or the group's "
                                 "manifest is not published yet)")))
            return
        self._net.send_via(msg._conn, Message(
            src=0, dst=msg.src, type=MsgType.Control_Reply_Layout,
            msg_id=msg.msg_id, req_id=msg.req_id,
            data=wire.encode(layout)))

    def _deregister_client(self, msg: Message) -> None:
        # Graceful close. Slot recycling is async-server only: the sync
        # server's per-worker clocks/finished flags are positional history
        # a newcomer must not inherit, so BSP keeps the reference's
        # static-membership contract (a departed worker's slot stays
        # retired; crashed clients are reclaimed only by lease eviction).
        # Only the connection that leased the slot may free it: a
        # duplicate, forged, or replayed deregister (src=-1, a local id,
        # a replay after the slot was re-leased) must not let two later
        # clients share one worker id. A recycled slot DOES inherit the
        # departed client's per-worker updater state (momentum/adagrad
        # accumulators) — deliberate: that state is the slot's
        # optimization history, exactly what the reference's static
        # membership kept positional.
        from multiverso_tpu.runtime.server import SyncServer
        slot = int(msg.src)
        conn = getattr(msg, "_conn", None)
        with self._wid_lock:
            if conn is None or self._leased.get(slot) is not conn:
                log.error("remote: ignoring deregister for slot %d "
                          "(not leased to this connection)", slot)
                return
            self.liveness.forget(slot)
            # drop session claims on the slot so a stale client cannot
            # resume a slot later re-leased to someone else
            self._sessions = {s: w for s, w in self._sessions.items()
                              if w != slot}
            if not isinstance(self._zoo.server, SyncServer):
                del self._leased[slot]
                self._free_slots.append(slot)

    def _resume_slot(self, session: int, resume: int,
                     msg: Message) -> Optional[str]:
        """Validate a reconnect-and-resume claim (``_wid_lock`` held);
        returns a refusal message or None (granted, caller re-leases).
        The session nonce — not the connection, which is typically dead —
        is the authority for slot ownership."""
        base = self._zoo.num_workers - self._zoo.remote_workers
        idx = resume - base
        if not 0 <= idx < self._zoo.remote_workers:
            return f"cannot resume worker {resume}: not a remote slot"
        if self.liveness.is_evicted(resume):
            return (f"worker {resume} was evicted (lease expired); its "
                    "round-clock history is retired — register fresh")
        if session and self._sessions.get(session) == resume:
            return None  # the same client reclaiming its own slot
        held = self._leased.get(resume)
        if held is msg._conn:
            return None  # replayed register on the same connection
        if held is None:
            # unleased: a restarted server (empty lease table) or a
            # gracefully-freed slot; account it as taken
            if idx >= self._next_remote:
                for skipped in range(self._next_remote, idx):
                    self._free_slots.append(base + skipped)
                self._next_remote = idx + 1
            elif resume in self._free_slots:
                self._free_slots.remove(resume)
            else:
                return f"worker slot {resume} is not resumable"
            return None
        return f"worker slot {resume} is leased to another client"

    def _register_reply(self, msg: Message, payload: Any) -> None:
        reply = Message(src=msg.dst, dst=msg.src,
                        type=MsgType.Control_Reply_Register,
                        msg_id=msg.msg_id, req_id=msg.req_id,
                        data=wire.encode(payload))
        self._dedup_store(msg.req_id, reply)
        self._net.send_via(msg._conn, reply)

    def _register_client(self, msg: Message) -> None:
        info = wire.decode(msg.data)
        info = info if isinstance(info, dict) else {}
        session = int(info.get("session", 0))
        resume = int(info.get("resume", -1))
        base = self._zoo.num_workers - self._zoo.remote_workers
        with self._wid_lock:
            if resume >= 0:
                refusal = self._resume_slot(session, resume, msg)
                if refusal is not None:
                    self._register_reply(msg, {"error": refusal})
                    return
                worker_id = resume
            elif self._free_slots:
                worker_id = self._free_slots.pop()
            elif self._next_remote >= self._zoo.remote_workers:
                # refuse: an out-of-range worker id would alias slot-0
                # per-worker state and bypass the BSP clocks
                self._register_reply(msg, {"error": (
                    f"all {self._zoo.remote_workers} remote worker slots "
                    "are taken (raise the remote_workers flag at init)")})
                return
            else:
                worker_id = base + self._next_remote
                self._next_remote += 1
            self._leased[worker_id] = msg._conn
            if session:
                self._sessions[session] = worker_id
        self.liveness.register(worker_id)
        directory = []
        # snapshot: create_table on the main thread mutates the dict
        for table_id, table in list(self._zoo.server._tables.items()):
            spec = table.remote_spec()
            if spec is not None:
                entry = {"table_id": table_id, **spec}
                offset = int(getattr(table, "row_offset", 0) or 0)
                if offset:
                    # range-sharded member: this table's rows/keys sit at
                    # [offset, offset + local size) of the global table —
                    # introspection for routers and operators
                    entry["row_offset"] = offset
                directory.append(entry)
        self._register_reply(msg, {"worker_id": worker_id,
                                   "num_workers": self._zoo.num_workers,
                                   "tables": directory})


# -- one-shot control probes --------------------------------------------------

def control_probe(endpoint: str, request_type: MsgType,
                  reply_type: MsgType, timeout: float = 10.0,
                  what: str = "probe", payload: Any = None) -> Any:
    """Dial ``endpoint``, send one control frame, return the decoded
    reply payload. The shared skeleton under the stats and layout RPCs —
    deliberately NOT a RemoteClient: no worker slot, no lease, no chaos
    transport, because a diagnostic/bootstrap probe must work when the
    data plane is the thing being diagnosed. A ``Reply_Error`` answer
    (e.g. asking a non-member for a shard layout) raises RuntimeError
    with the server's message."""
    net = TcpNet()
    net.rank = -1
    net.connect([endpoint])
    msg_id = next_msg_id()
    got = threading.Event()
    box: Dict[str, Message] = {}

    def pump() -> None:
        try:
            while True:
                msg = net.recv()
                if msg is None:
                    return
                if msg.msg_id == msg_id:
                    box["reply"] = msg
                    got.set()
                    return
        except ConnectionError:
            got.set()

    threading.Thread(target=pump, daemon=True,
                     name=f"mv-{what}-probe").start()
    try:
        net.send(Message(src=-1, dst=0, type=request_type, msg_id=msg_id,
                         data=wire.encode(payload)
                         if payload is not None else []))
        if not got.wait(timeout):
            raise TimeoutError(f"{what} probe to {endpoint} timed out "
                               f"after {timeout:.1f}s")
    finally:
        net.finalize()
    reply = box.get("reply")
    if reply is None:
        raise ConnectionError(f"{what} probe to {endpoint}: connection "
                              "lost before the reply")
    if reply.type == MsgType.Reply_Error:
        raise RuntimeError(f"{what} probe to {endpoint} refused: "
                           f"{wire.decode(reply.data)}")
    if reply.type != reply_type:
        raise RuntimeError(f"{what} probe to {endpoint}: unexpected reply "
                           f"{reply.type}")
    return wire.decode(reply.data)


def fetch_watermark(endpoint: str, timeout: float = 10.0) -> Dict[str, Any]:
    """One-shot watermark probe: ``{"role": "primary"|"replica",
    "watermark": <applied/append seq>, "primary_watermark": <append seq
    observed>, "lag": <records behind>}`` — the staleness position of any
    serving endpoint (primary or read replica), slot-free."""
    return control_probe(endpoint, MsgType.Control_Watermark,
                         MsgType.Control_Reply_Watermark,
                         timeout=timeout, what="watermark")


def fetch_traces(endpoint: str, timeout: float = 10.0) -> Dict[str, Any]:
    """One-shot trace pull: ``{"role", "endpoint", "t_reply_ns",
    "traces": {req_id: [[stage, t_ns], ...]}}`` from any serving process
    (primary or replica), slot-free. Wire keys arrive as strings/ints
    depending on codec; the collector normalizes."""
    return control_probe(endpoint, MsgType.Control_Traces,
                         MsgType.Control_Reply_Traces,
                         timeout=timeout, what="traces")


def fetch_profile(endpoint: str, timeout: float = 10.0) -> Dict[str, Any]:
    """One-shot profile pull: ``{"role", "endpoint", "t_reply_ns",
    "profile": <SamplingProfiler.report()>}`` from any serving process
    (primary or replica), slot-free. The report is empty-but-valid when
    the remote runs without ``profile_continuous``."""
    return control_probe(endpoint, MsgType.Control_Profile,
                         MsgType.Control_Reply_Profile,
                         timeout=timeout, what="profile")


def fetch_stats(endpoint: str, timeout: float = 10.0) -> StatsSnapshot:
    """One-shot live stats RPC: the server's dashboard as a
    :class:`StatsSnapshot` (histograms rebuilt from their bucket arrays,
    so p50/p95/p99 compute caller-side on the server's exact counts)."""
    return StatsSnapshot(control_probe(endpoint, MsgType.Control_Stats,
                                       MsgType.Control_Reply_Stats,
                                       timeout=timeout, what="stats"))


def fetch_digest(endpoint: str, timeout: float = 30.0) -> Dict[str, Any]:
    """One-shot state-digest probe: ``{"role", "endpoint", "watermark",
    "layout_version", "tables": {tid: {"digest", "rows"}}}`` from any
    serving process — primary, replica, or standby serving reads —
    computed under its dispatcher seam so the (digest, watermark) pair
    is exact. Slot-free. The fleet auditor (obs/audit.py) compares
    these across roles at a common watermark."""
    return control_probe(endpoint, MsgType.Control_Digest,
                         MsgType.Control_Reply_Digest,
                         timeout=timeout, what="digest")


def fetch_cut(endpoint: str, cut_id: str, timeout: float = 120.0,
              kill: str = "") -> Dict[str, Any]:
    """One-shot consistent-cut marker: ask a shard primary to snapshot
    every table at its WAL fence into ``cut_<cut_id>/`` and reply
    ``{"cut_id", "fence", "segment", "cut_dir", "digests", "tables",
    "dedup_count"}``. ``kill="shard"`` rides the payload for the
    MV_CUT_KILL chaos drill (the shard dies after its snapshot, before
    replying — the coordinator must fail the whole cut)."""
    return control_probe(endpoint, MsgType.Control_Cut,
                         MsgType.Control_Reply_Cut, timeout=timeout,
                         what="cut", payload={"cut_id": str(cut_id),
                                              "kill": kill or ""})


# -- client side -------------------------------------------------------------

class RemoteChannel:
    """WorkerTable request channel that frames requests over TCP."""

    def __init__(self, client: "RemoteClient") -> None:
        self._client = client

    def worker_id(self) -> int:
        return self._client.worker_id

    def submit(self, table_id: int, msg_type: MsgType, request: Any,
               msg_id: int, completion: Completion) -> None:
        self._client._send(table_id, msg_type, request, msg_id, completion)

    def post(self, table_id: int, msg_type: MsgType) -> None:
        self._client._send(table_id, msg_type, None, next_msg_id(), None)


class DeadlineMinter:
    """Mints the absolute monotonic deadline stamped on every correlated
    Get/Add from the ``request_deadline_seconds`` budget.

    With ``deadline_tighten_ratio`` > 0 the minted budget tracks the SLO
    burn engine: while any objective fires, each mint shrinks the
    effective budget geometrically (``_STEP`` per mint) toward the floor
    ``ratio x budget`` — backlog age follows the error budget instead of
    queueing 30-second hopes behind a burning fleet — and when the burn
    clears, mints recover geometrically back to the full budget. Both
    transitions are flight-recorded (``deadline_tighten`` /
    ``deadline_recovered``), every tightened mint counts
    ``DEADLINE_TIGHTENED``, and the live scale is the ``DEADLINE_SCALE``
    gauge.

    With ``ratio <= 0`` (the default) ``mint()`` evaluates exactly the
    legacy expression — bit-identical minting, no metrics touched."""

    _STEP = 0.7  # geometric per-mint step toward the floor (and back)

    def __init__(self, budget: float, ratio: float = 0.0,
                 burn: Optional[Callable[[], bool]] = None) -> None:
        self.budget = float(budget)
        self.ratio = min(1.0, float(ratio))
        self.scale = 1.0
        # test seam; None = probe the process-global SLO engine
        self._burn = burn

    def _burning(self) -> bool:
        if self._burn is not None:
            return bool(self._burn())
        import multiverso_tpu as mv
        engine = mv.slo_engine()
        return bool(engine is not None and engine.firing())

    def mint(self) -> float:
        """The absolute monotonic deadline for one request (0.0 =
        no deadline)."""
        if self.ratio <= 0 or self.budget <= 0:
            return (time.monotonic() + self.budget
                    if self.budget > 0 else 0.0)
        scale = self.scale
        if self._burning():
            tightened = max(self.ratio, scale * self._STEP)
            if scale >= 1.0 and tightened < 1.0:
                flight_dump("deadline_tighten", budget=self.budget,
                            floor=self.ratio, scale=tightened)
            scale = tightened
        elif scale < 1.0:
            scale = min(1.0, scale / self._STEP)
            if scale >= 1.0:
                flight_dump("deadline_recovered", budget=self.budget)
        if scale < 1.0:
            count("DEADLINE_TIGHTENED")
        if scale != self.scale:
            self.scale = scale
            gauge_set("DEADLINE_SCALE", scale)
        return time.monotonic() + self.budget * scale


class _Inflight:
    """One outstanding correlated request: the framed message (for
    retransmission) plus its retry clock. ``first`` is the issue time —
    the request-latency histogram measures from here, so retransmits
    lengthen (never reset) the observed latency."""

    __slots__ = ("msg", "sent", "first", "attempts")

    def __init__(self, msg: Message, sent: float) -> None:
        self.msg = msg
        self.sent = sent
        self.first = sent
        self.attempts = 0


class RemoteClient:
    """Off-mesh table client: register → worker id + table directory.

    Survives faults (``docs/fault_tolerance.md``): correlated requests are
    kept in an inflight set and retransmitted on reply timeout
    (``request_retry_seconds``) or after reconnect-and-resume
    (``reconnect_deadline_seconds``); the server's dedup window keeps every
    replay idempotent. A maintenance thread renews the worker's lease with
    heartbeats. ``reconnect_deadline_seconds=0`` restores the fail-fast
    posture: any connection loss fails all pending requests immediately.

    Read tier (``docs/serving.md``): with ``read_endpoints`` (serving
    read replicas) and a non-primary ``read_preference``, Gets route
    through :class:`~multiverso_tpu.runtime.read.ReadRouter` — client
    cache, then budget-admitted replicas (hedged optionally), then the
    primary as the transparent fallback. Adds always go to the primary.
    Pipelined tables bypass the tier (their Gets depend on per-worker
    server state a replica does not track)."""

    def __init__(self, endpoint: str, timeout: float = 30.0,
                 read_endpoints: Optional[List[str]] = None,
                 read_preference: Optional[str] = None) -> None:
        self._net = make_net()
        self._net.rank = -1
        self._net.connect([endpoint])
        self._pending: Dict[int, Completion] = {}
        self._inflight: Dict[int, _Inflight] = {}
        self._lock = threading.Lock()
        self._compress = bool(config.get_flag("wire_compression"))
        self._trace = bool(config.get_flag("trace_requests"))
        # 31-bit nonzero session nonce: req_id = (session << 32) | seq
        # stays within the header's signed 64-bit field
        self._session = random.getrandbits(31) | 1
        self._req_seq = itertools.count(1)
        self._closed = False
        self._recovering = False
        self._recover_lock = threading.Lock()
        self._stop_maint = threading.Event()
        self._hb_period = float(config.get_flag("heartbeat_seconds"))
        self._rto = float(config.get_flag("request_retry_seconds"))
        # overload survival (fault/retry.py): deadline budget stamped on
        # every correlated request (0 = none), a success-refilled retry
        # budget governing retransmits + read hedges, and a circuit
        # breaker that fails writes fast while the server is suspect.
        # Defaults leave all three inert.
        self._deadline_budget = float(
            config.get_flag("request_deadline_seconds"))
        self._minter = DeadlineMinter(
            self._deadline_budget,
            float(config.get_flag("deadline_tighten_ratio")))
        self._retry_budget = RetryBudget.from_flags()
        self._breaker = CircuitBreaker.from_flags()
        # set BEFORE the pump starts (the pump observes reply watermarks
        # through it); the router itself is built after registration
        self._read_router = None
        self._read_ok: Dict[int, bool] = {}
        self._pump_thread = threading.Thread(
            target=self._pump, daemon=True, name="mv-remote-client")
        self._pump_thread.start()
        self.worker_id = -1
        self.directory: List[Dict[str, Any]] = []
        self.num_workers = 0
        try:
            self._register(timeout)
        except BaseException:
            self._net.finalize()
            raise
        self._channel = RemoteChannel(self)
        preference = (read_preference if read_preference is not None
                      else str(config.get_flag("read_preference")))
        if read_endpoints and preference != "primary":
            from multiverso_tpu.runtime.read import ReadRouter

            def primary_submit(table_id, request, completion):
                self._send(table_id, MsgType.Request_Get, request,
                           next_msg_id(), completion, direct=True)

            def primary_query_submit(table_id, request, completion):
                self._send(table_id, MsgType.Request_Query, request,
                           next_msg_id(), completion, direct=True)

            self._read_router = ReadRouter(
                list(read_endpoints), preference, primary_submit,
                req_id_source=(self._next_req_id if self._trace else None),
                watermark_confirm=(
                    self._confirm_watermark
                    if self._trace
                    and bool(config.get_flag("trace_read_confirm"))
                    else None),
                retry_budget=self._retry_budget,
                primary_query_submit=primary_query_submit)
        self._start_maintenance()

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop_maint.set()
        if self._read_router is not None:
            self._read_router.close()
        try:
            self._net.send(Message(src=self.worker_id, dst=0,
                                   type=MsgType.Control_Deregister,
                                   msg_id=next_msg_id()))
        except OSError:
            pass  # server already gone; slot stays leased (static membership)
        self._net.finalize()

    def _next_req_id(self) -> int:
        return (self._session << 32) | (next(self._req_seq) & 0xFFFFFFFF)

    def _confirm_watermark(self, req_id: int) -> None:
        """Read-tier trace confirm: fire one slot-free Control_Watermark
        at the primary stamped with a replica-served Get's req_id. The
        reply both extends the trace across the primary (the 'watermark
        path' leg of a stitched span) and advances the read cache's
        horizon off the authoritative append watermark. Fire-and-forget:
        a lost frame just shortens the trace."""
        try:
            self._net.send(Message(
                src=self.worker_id, dst=0, type=MsgType.Control_Watermark,
                msg_id=next_msg_id(), req_id=req_id, trace=True))
        except OSError:
            pass  # diagnostics never trip recovery; the read already won

    def _register(self, timeout: float, resume: bool = False) -> None:
        """Register (or resume) this client's worker slot. The request is
        re-sent once a second until the reply lands or ``timeout`` passes —
        registration rides the same lossy wire as everything else, and the
        server's dedup window makes the replay idempotent."""
        msg_id = next_msg_id()
        completion = Completion()
        with self._lock:
            self._pending[msg_id] = completion
        payload: Dict[str, Any] = {"session": self._session}
        if resume:
            payload["resume"] = self.worker_id
        msg = Message(src=self.worker_id if resume else -1, dst=0,
                      type=MsgType.Control_Register, msg_id=msg_id,
                      req_id=self._next_req_id(), data=wire.encode(payload))
        deadline = time.monotonic() + timeout
        while True:
            try:
                self._net.send(msg)
                info = completion.wait(
                    min(1.0, max(0.05, deadline - time.monotonic())))
                break
            except TimeoutError:  # before OSError: TimeoutError IS one
                if time.monotonic() >= deadline:
                    with self._lock:
                        self._pending.pop(msg_id, None)
                    raise TimeoutError(
                        "remote registration timed out") from None
            except OSError:
                with self._lock:
                    self._pending.pop(msg_id, None)
                raise  # caller's retry loop owns the backoff
        if "error" in info:
            raise RuntimeError(f"remote registration refused: {info['error']}")
        self.worker_id = int(info["worker_id"])
        self.num_workers = int(info["num_workers"])
        self.directory = info["tables"]

    # -- request path --------------------------------------------------------
    def _read_tier_ok(self, table_id: int) -> bool:
        """Tables whose Gets may route through the read tier: everything
        except pipelined tables (their Gets read per-worker server state
        — what THIS worker has seen — which replicas don't track)."""
        ok = self._read_ok.get(table_id)
        if ok is None:
            spec = next((s for s in self.directory
                         if int(s.get("table_id", -1)) == int(table_id)),
                        None)
            ok = spec is not None and not spec.get("is_pipelined", False)
            self._read_ok[table_id] = ok
        return ok

    def _send(self, table_id: int, msg_type: MsgType, request: Any,
              msg_id: int, completion: Optional[Completion],
              direct: bool = False, watermark: int = -1,
              deadline: Optional[float] = None) -> int:
        """Returns the req_id the request was issued under (0 for
        fire-and-forget posts) so callers a layer up — the shard router —
        can append their own hops to the same trace. ``deadline`` is an
        absolute monotonic instant (None = mint one from the
        request_deadline_seconds flag; 0.0 = explicitly none)."""
        if self._read_router is not None and not direct:
            if (msg_type == MsgType.Request_Get and completion is not None
                    and self._read_tier_ok(table_id)):
                return self._read_router.submit_get(table_id, request,
                                                    completion)
            if (msg_type == MsgType.Request_Query and completion is not None
                    and self._read_tier_ok(table_id)):
                # top-k pushdown rides the same read tier: replica-first
                # with budget admission, caching and hedging, primary
                # fallback via direct=True
                return self._read_router.submit_query(table_id, request,
                                                      completion)
            if msg_type == MsgType.Request_Add:
                # this client just changed the table: its cached reads of
                # it are suspect (write-through invalidation)
                self._read_router.note_local_write(table_id)
        if completion is not None and msg_type in (MsgType.Request_Get,
                                                   MsgType.Request_Add,
                                                   MsgType.Request_Query):
            if deadline is None:
                deadline = self._minter.mint()
            if deadline > 0 and deadline <= time.monotonic():
                # the caller's budget is already gone: spending a round
                # trip to learn that would be the overload amplifier this
                # layer exists to remove
                count("DEADLINE_EXPIRED_AT_SEND")
                completion.fail(RuntimeError(
                    f"deadline_exceeded: {msg_type.name} expired before "
                    "send"))
                return 0
            if not self._breaker.allow():
                # tripped breaker: fail fast with the truth instead of
                # queueing onto a server we believe is down. Replica-
                # routed Gets never reach here — they were submitted to
                # the read tier above.
                count("BREAKER_FAST_FAILS")
                completion.fail(RuntimeError(
                    "circuit open: server connection suspect after "
                    "consecutive failures; failing fast (half-open probe "
                    f"in <= {self._breaker.reset_seconds:.1f}s)"))
                return 0
        data = [] if request is None and msg_type not in (
            MsgType.Request_Get, MsgType.Request_Add) else wire.encode(
                request, compress=self._compress)
        msg = Message(src=self.worker_id, dst=0, type=msg_type,
                      table_id=table_id, msg_id=msg_id,
                      deadline=deadline if deadline is not None else 0.0,
                      req_id=self._next_req_id() if completion is not None
                      else 0,
                      # a shard router stamps its layout version here so a
                      # mid-migration donor refuses (Reply_WrongShard)
                      # instead of applying a possibly-misrouted request;
                      # plain clients leave -1 (never fenced)
                      watermark=watermark,
                      trace=self._trace and completion is not None,
                      data=data)
        with self._lock:
            if completion is not None:
                self._pending[msg_id] = completion
                self._inflight[msg_id] = _Inflight(msg, time.monotonic())
                gauge_set("CLIENT_INFLIGHT", len(self._inflight))
                hop(msg.req_id, "client_send")
                if msg_type in (MsgType.Request_Get, MsgType.Request_Add,
                                MsgType.Request_Query):
                    # chargeback plane: stamp the span with its tenant and
                    # meter the payload bytes it pushed onto the wire
                    tenant = resolve_tenant(table_id)
                    tag_tenant(msg.req_id, tenant)
                    count(f"TENANT_{tenant}_BYTES",
                          sum(int(getattr(b, "nbytes", 0) or len(b))
                              for b in data))
            if self._recovering:
                # recovery retransmits the whole inflight set (in req_id
                # order) once re-registered; sending now would race it
                return msg.req_id
        try:
            self._net.send(msg)
        except OSError:
            if completion is None:
                raise  # fire-and-forget posts keep the fail-loud contract
            self._start_recovery()  # the request stays inflight; recovery
            # (or its deadline) settles the completion
        return msg.req_id

    def _pump(self) -> None:
        while True:
            try:
                msg = self._net.recv()
            except ConnectionError:
                if not self._closed:
                    self._start_recovery()
                continue
            if msg is None:
                self._fail_all(ConnectionError("remote client shut down"))
                return
            if self._read_router is not None and msg.watermark >= 0:
                # primary replies advertise the append watermark: the
                # cache horizon advances (and a regression — a new
                # primary incarnation — flushes it)
                self._read_router.observe_primary_watermark(msg.watermark)
            if msg.type == MsgType.Control_Reply_Watermark:
                # the read tier's trace confirm coming home: no pending
                # completion (fire-and-forget), but the hop closes the
                # client↔primary request/reply pair the clock-offset
                # estimator needs
                hop(msg.req_id, "client_watermark_reply")
                continue
            with self._lock:
                completion = self._pending.pop(msg.msg_id, None)
                flight = self._inflight.pop(msg.msg_id, None)
                gauge_set("CLIENT_INFLIGHT", len(self._inflight))
            if completion is None:
                continue  # duplicate reply (retransmit + dedup): settled
            # ANY correlated reply — success or server-side error — proves
            # the connection lives: refill the retry budget, feed the
            # breaker (its failure signal is silence, not error payloads)
            self._retry_budget.on_success()
            self._breaker.record_success()
            if flight is not None:
                # end-to-end request latency, retransmits included — the
                # distribution mv.stats() reports as CLIENT_REQUEST_SECONDS
                observe("CLIENT_REQUEST_SECONDS",
                        time.monotonic() - flight.first)
            hop(msg.req_id, "client_reply")
            try:
                if msg.type == MsgType.Reply_Error:
                    text = wire.decode(msg.data)
                    if (isinstance(text, str) and text.startswith("shed:")
                            and flight is not None
                            and flight.msg.type == MsgType.Request_Add):
                        # admission-shed training write: the graceful-
                        # degradation contract — the delta is DROPPED (a
                        # lost async gradient, Downpour-tolerated), the
                        # caller is not errored, the shed is counted
                        count("CLIENT_ADDS_SHED")
                        completion.done(None)
                    else:
                        completion.fail(RuntimeError(
                            f"server-side failure: {text}"))
                elif msg.type == MsgType.Reply_WrongShard:
                    refusal = wire.decode(msg.data)
                    completion.fail(WrongShardError(
                        refusal.get("layout_version", 0),
                        refusal.get("manifest")))
                elif msg.type == MsgType.Reply_Add:
                    completion.done(None)
                else:
                    completion.done(wire.decode(msg.data))
            except Exception as exc:  # noqa: BLE001 — a malformed reply must
                # fail its waiter, not kill the pump (which would hang every
                # later request forever)
                completion.fail(exc)

    # -- fault recovery ------------------------------------------------------
    def _start_recovery(self) -> None:
        # connection loss is the strongest failure signal the breaker gets
        self._breaker.record_failure()
        with self._recover_lock:
            if self._recovering or self._closed:
                return
            self._recovering = True
        threading.Thread(target=self._recover, daemon=True,
                         name="mv-remote-reconnect").start()

    def _recover(self) -> None:
        """Reconnect-and-resume: re-register under the same session (the
        server re-leases the same worker id) with backoff until the
        deadline, then retransmit every inflight request in issue order —
        the server's dedup window drops the ones that already applied.
        Deadline exhaustion (or a refusal — evicted slot, capacity) fails
        all pending requests with a clean error: the pre-tentpole fail-fast
        behavior, just ``reconnect_deadline_seconds`` later."""
        policy = RetryPolicy.from_flags()
        last_error: BaseException = ConnectionError("connection lost")
        resumed = False
        try:
            for _attempt, remaining in policy.attempts():
                if self._closed:
                    return
                try:
                    self._register(timeout=min(2.0, max(0.1, remaining)),
                                   resume=True)
                except RuntimeError as exc:
                    self._fail_all(exc)  # refused: permanent, stop retrying
                    return
                except (OSError, TimeoutError) as exc:
                    last_error = exc
                    continue
                with self._lock:
                    backlog = sorted(self._inflight.values(),
                                     key=lambda f: f.msg.req_id)
                    # cleared under _lock: a concurrent _send either saw
                    # _recovering and left its message to this backlog, or
                    # runs after the backlog went out — never both
                    self._recovering = False
                    resumed = True
                    now = time.monotonic()
                    for flight in backlog:
                        flight.attempts += 1
                        flight.sent = now
                        hop(flight.msg.req_id, "client_resume_retransmit")
                        try:
                            self._net.send(flight.msg)
                        except OSError as exc:
                            # died again mid-resume: the pump's next
                            # sentinel starts a fresh recovery; unsent
                            # entries stay inflight for it
                            last_error = exc
                            break
                count("CLIENT_RECONNECTS")
                log.info("remote client %d: reconnected, %d request(s) "
                         "retransmitted", self.worker_id, len(backlog))
                return
            self._fail_all(ConnectionError(
                "server connection lost; reconnect gave up after "
                f"{policy.deadline:.1f}s (last error: {last_error!r})"))
        finally:
            if not resumed:
                with self._recover_lock:
                    self._recovering = False

    def _start_maintenance(self) -> None:
        """Heartbeats (lease renewal) + reply-timeout retransmission; no
        thread at all when both are disabled."""
        periods = [p for p in (self._hb_period, self._rto) if p > 0]
        if not periods:
            return
        tick = max(0.05, min(min(periods) / 4.0, 1.0))
        threading.Thread(target=self._maintain, args=(tick,), daemon=True,
                         name="mv-remote-maint").start()

    def _maintain(self, tick: float) -> None:
        last_beat = 0.0
        while not self._stop_maint.wait(tick):
            if self._closed:
                return
            if self._recovering:
                continue  # recovery owns the connection right now
            now = time.monotonic()
            if (self._hb_period > 0 and self.worker_id >= 0
                    and now - last_beat >= self._hb_period):
                last_beat = now
                try:
                    self._net.send(Message(
                        src=self.worker_id, dst=0,
                        type=MsgType.Control_Heartbeat,
                        msg_id=next_msg_id()))
                except OSError:
                    self._start_recovery()
                    continue
            if self._rto > 0:
                self._retransmit_stale(now)

    def _retransmit_stale(self, now: float) -> None:
        """Re-send correlated requests whose reply is overdue (per-request
        exponential backoff on the timeout). Safe against legitimately
        slow replies — a BSP-gated Get, a busy dispatcher — because the
        server's dedup window swallows the replay."""
        with self._lock:
            if self._recovering:
                return
            stale = []
            for f in self._inflight.values():
                if now - f.sent < self._rto * min(2 ** f.attempts, 16):
                    continue
                # every overdue reply is a failure datapoint for the
                # breaker whether or not the retransmit is admitted
                self._breaker.record_failure()
                if not self._retry_budget.allow():
                    # dry retry budget DEFERS (never fails): sent/attempts
                    # stay put, so the flight re-qualifies next tick and
                    # retries once successes refill the bucket
                    break
                f.attempts += 1
                f.sent = now
                stale.append(f)
        for flight in stale:
            count("CLIENT_RETRIES")
            hop(flight.msg.req_id, "client_retransmit")
            log.debug("remote client %d: retransmitting %s (attempt %d)",
                      self.worker_id, flight.msg.type, flight.attempts)
            try:
                self._net.send(flight.msg)
            except OSError:
                self._start_recovery()
                return

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
            self._inflight.clear()
            gauge_set("CLIENT_INFLIGHT", 0)
        if pending:
            # unclean end of session: every in-flight request dies with
            # this error — capture the hop traces while they are fresh
            flight_dump("client_fail_all", worker=self.worker_id,
                        pending=len(pending), error=repr(exc))
        for completion in pending:
            completion.fail(exc)

    # -- table proxies -------------------------------------------------------
    def table(self, table_id: int) -> WorkerTable:
        """Build the worker proxy matching the server table's directory
        entry. Proxies share all shaping code with the in-process workers."""
        spec = next((s for s in self.directory
                     if s["table_id"] == table_id), None)
        if spec is None:
            raise KeyError(f"no remotable table with id {table_id}; "
                           f"directory: {self.directory}")
        kind = spec["kind"]
        if kind == "array":
            return _RemoteArrayWorker(spec, table_id, self._channel)
        if kind == "matrix":
            return _RemoteMatrixWorker(spec, table_id, self._channel)
        if kind == "kv":
            return _RemoteKVWorker(spec, table_id, self._channel)
        if kind == "sparse":
            return _RemoteSparseWorker(spec, table_id, self._channel)
        raise KeyError(f"unknown remote table kind {kind!r}")

    def tables(self) -> List[WorkerTable]:
        return [self.table(s["table_id"]) for s in self.directory]


def _make_error_feedback(shape, dtype) -> Optional[Any]:
    """Per-proxy ErrorFeedback when -wire_quant_bits is set (float32
    tables only — quantization targets gradient-delta payloads)."""
    bits = int(config.get_flag("wire_quant_bits"))
    if bits <= 0 or np.dtype(dtype) != np.float32:
        return None
    from multiverso_tpu.utils.quantization import ErrorFeedback
    return ErrorFeedback(shape, bits)


def merge_duplicate_rows(ids: np.ndarray, values: np.ndarray):
    """Pre-aggregate duplicate row ids so every touched row's error-
    feedback residual is read and written exactly once — duplicates would
    otherwise share one residual read and last-write the update,
    permanently losing part of the feedback. Shared by the per-proxy EF
    path, the shard router's per-shard EF path, and the dispatcher's
    fused-apply merge (tables.matrix_table.merge_add_requests).

    Implementation note: copy each unique id's FIRST row, then sum only
    the (few) genuinely duplicated groups — NOT ``np.add.at`` (the
    unbuffered ufunc.at path) or ``np.add.reduceat`` over 2-D rows, both
    of which cost more on row-matrix payloads than the fused scatter
    they feed saves (measured 6 ms / 12 ms vs ~1 ms per 6k×128 merge)."""
    id_arr = np.asarray(ids)
    uniq, inverse, counts = np.unique(id_arr, return_inverse=True,
                                      return_counts=True)
    if len(uniq) == len(id_arr):
        return ids, values
    values = np.asarray(values)
    order = np.argsort(inverse, kind="stable")
    starts = np.cumsum(counts) - counts
    merged = values[order[starts]]  # fancy index: a fresh writable array
    for g in np.nonzero(counts > 1)[0]:
        s = starts[g]
        merged[g] = values[order[s:s + counts[g]]].sum(axis=0)
    return uniq.astype(id_arr.dtype, copy=False), merged


class _RemoteArrayWorker(ArrayWorker):
    """ArrayWorker shaping over the wire (no server construction)."""

    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.size = int(spec["size"])
        self.dtype = np.dtype(spec["dtype"])
        self._ef = _make_error_feedback((self.size,), self.dtype)

    def _submit(self, msg_type, request):
        # quantize ADD deltas on the way out (error feedback keeps the
        # lost precision in the client residual) — the server decodes to
        # plain float32 before process_add
        if (self._ef is not None and msg_type == MsgType.Request_Add
                and isinstance(request, tuple) and len(request) >= 2
                and isinstance(request[0], np.ndarray)
                and request[0].dtype == np.float32):
            request = (self._ef.compress(request[0]),) + request[1:]
        return super()._submit(msg_type, request)

    # device IO is in-process only (a remote hop IS a host hop); without
    # this override the class attribute inherited from ArrayWorker would
    # send per-leaf device requests over TCP
    supports_device_io = False

    def get_device(self):
        raise RuntimeError("get_device() needs mesh residency; remote "
                           "clients are off-mesh — use get()")

    def get_device_async(self, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "get/get_async (host arrays)")

    def add_device_async(self, delta, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def sync_leaves_async(self, delta_leaves, option=None, last_leaves=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def push_leaves_async(self, new_leaves, last_leaves, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def get_leaves_async(self, template_leaves, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "get/get_async (host arrays)")


class _RemoteMatrixWorker(MatrixWorker):
    """MatrixWorker shaping (row buckets, sparse cache, option defaults)
    over the wire. Device IO is in-process only (the whole point is
    skipping the host hop; a remote hop IS a host hop) — callers branch on
    ``supports_device_io``."""

    supports_device_io = False

    def get_device_async(self, row_ids, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "get/get_async (host arrays)")

    def transact_device_async(self, fn, others, args=(), touched=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def add_device_async(self, values, row_ids, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.num_row = int(spec["num_row"])
        self.num_col = int(spec["num_col"])
        self.dtype = np.dtype(spec["dtype"])
        self._ef = _make_error_feedback((self.num_row, self.num_col),
                                        self.dtype)
        self.is_sparse = bool(spec.get("is_sparse", False))
        self._init_client_state(bool(spec.get("is_pipelined", False)),
                                int(spec.get("num_workers", 1)))

    def _submit(self, msg_type, request):
        # quantize row-delta ADDs with per-row error feedback (whole-table
        # adds use ids=None -> full-shape residual)
        if (self._ef is not None and msg_type == MsgType.Request_Add
                and isinstance(request, tuple) and len(request) == 3
                and isinstance(request[1], np.ndarray)
                and request[1].dtype == np.float32):
            ids, values, option = request
            if ids is not None:
                ids, values = merge_duplicate_rows(ids, values)
            request = (ids, self._ef.compress(values, ids), option)
        return super()._submit(msg_type, request)

    def get_device(self):
        raise RuntimeError("get_device() needs mesh residency; remote "
                           "clients are off-mesh — use get()")


class _RemoteKVWorker(KVWorker):
    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.value_dtype = np.dtype(spec["dtype"])
        self._raw: Dict[int, Any] = {}


class _RemoteSparseWorker(SparseWorker):
    """Sparse-key table shaping (O(nnz) get/add, counters) over the wire."""

    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.key_space = int(spec["key_space"])
        self.width = int(spec["width"])
        self.dtype = np.dtype(spec["dtype"])
        self.elements_pushed = 0
        self.elements_pulled = 0
