"""Remote table serving — the cross-process parameter-server path.

Reference capability (not copied): a worker in ANY process reaches tables via
worker actor → Communicator → network → Server actor, with the reply
retracing the path (``src/worker.cpp:30-76``, ``src/communicator.cpp:69-105``,
``src/server.cpp:36-58``); external hosts registered through the Controller
(``src/controller.cpp:38-80``).

TPU-era design: ONE process owns the device mesh and runs the dispatcher
(:mod:`multiverso_tpu.runtime.server`); any other process is an off-mesh
client. :class:`RemoteServer` is the net↔dispatcher bridge — a pump thread
pops table-request frames from the TCP mailbox, decodes them into the same
request structures local workers enqueue, and attaches a completion that
frames the reply back over the socket the request arrived on (clients never
bind a listener). :class:`RemoteClient` registers (gets a worker id + the
table directory), then hands out worker-table proxies that share ALL client
shaping code with the in-process workers — only the channel differs — so the
BSP clocks, per-worker updater state, and option envelopes behave
identically across the wire.

Payloads ride the :mod:`multiverso_tpu.runtime.wire` codec; float32 arrays
are SparseFilter-compressed when the ``wire_compression`` flag is on and the
sparse form is smaller (the reference applied SparseFilter on exactly these
host hops, ``src/table/sparse_matrix_table.cpp:147-153``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from multiverso_tpu import config, log
from multiverso_tpu.runtime.message import Message, MsgType, next_msg_id
from multiverso_tpu.runtime.net import TcpNet
from multiverso_tpu.runtime import wire
from multiverso_tpu.tables.array_table import ArrayWorker
from multiverso_tpu.tables.base import Completion, WorkerTable
from multiverso_tpu.tables.kv_table import KVWorker
from multiverso_tpu.tables.matrix_table import MatrixWorker
from multiverso_tpu.tables.sparse_table import SparseWorker

# wire_quant_bits lives in config.py (must exist before this module is
# first imported so mv.init(wire_quant_bits=...) works)
config.define_bool("wire_compression", True,
                   "SparseFilter-compress float32 payloads on host hops "
                   "when the sparse form is smaller")


# -- server side -------------------------------------------------------------

class _NetCompletion:
    """Dispatcher completion that frames the result back over the wire."""

    __slots__ = ("_net", "_conn", "_template", "_compress")

    def __init__(self, net: TcpNet, conn, template: Message,
                 compress: bool) -> None:
        self._net = net
        self._conn = conn
        self._template = template
        self._compress = compress

    def _reply(self, msg_type: MsgType, payload: Any) -> None:
        t = self._template
        msg = Message(src=t.dst, dst=t.src, type=msg_type,
                      table_id=t.table_id, msg_id=t.msg_id,
                      data=wire.encode(payload, compress=self._compress))
        try:
            self._net.send_via(self._conn, msg)
        except OSError as exc:
            log.error("remote: reply to worker %d failed: %r", t.src, exc)

    def done(self, result: Any) -> None:
        reply_type = (MsgType.Reply_Get
                      if self._template.type == MsgType.Request_Get
                      else MsgType.Reply_Add)
        self._reply(reply_type, result)

    def fail(self, error: BaseException) -> None:
        self._reply(MsgType.Reply_Error, repr(error))


class RemoteServer:
    """Serves this process's tables to off-mesh clients over TCP."""

    def __init__(self, zoo) -> None:
        self._zoo = zoo
        self._net = TcpNet()
        self._thread: Optional[threading.Thread] = None
        self._wid_lock = threading.Lock()
        self._next_remote = 0
        self._free_slots: List[int] = []  # recycled by Control_Deregister
        # slot -> the connection that registered it: a deregister is honored
        # only from that connection, so a replayed/forged deregister cannot
        # free a slot that was re-leased to a different client
        self._leased: Dict[int, Any] = {}
        self.endpoint: Optional[str] = None

    def serve(self, endpoint: str = "127.0.0.1:0") -> str:
        """Bind + start the pump; returns the dialable endpoint."""
        self.endpoint = self._net.bind(0, endpoint)
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="mv-remote-serve")
        self._thread.start()
        return self.endpoint

    def stop(self) -> None:
        self._net.finalize()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- pump ---------------------------------------------------------------
    def _pump(self) -> None:
        compress = bool(config.get_flag("wire_compression"))
        while True:
            try:
                msg = self._net.recv()
            except ConnectionError:
                continue  # a client connection died; its waiters are remote
            if msg is None:
                return
            try:
                self._handle(msg, compress)
            except Exception as exc:  # noqa: BLE001 — keep serving
                log.error("remote server: error on %s: %r", msg.type, exc)
                _NetCompletion(self._net, msg._conn, msg, False).fail(exc)

    def _handle(self, msg: Message, compress: bool) -> None:
        if msg.type == MsgType.Control_Register:
            self._register_client(msg)
            return
        if msg.type == MsgType.Control_Deregister:
            # Graceful close recycles the slot — async server only. The
            # sync server's per-worker clocks/finished flags are positional
            # history a newcomer must not inherit, so BSP keeps the
            # reference's static-membership contract (a departed worker's
            # slot stays retired; crashed clients are never reclaimed).
            # Only the connection that leased the slot may free it: a
            # duplicate, forged, or replayed deregister (src=-1, a local id,
            # a replay after the slot was re-leased) must not let two later
            # clients share one worker id. A recycled slot DOES inherit the
            # departed client's per-worker updater state (momentum/adagrad
            # accumulators) — deliberate: that state is the slot's
            # optimization history, exactly what the reference's static
            # membership kept positional.
            from multiverso_tpu.runtime.server import SyncServer
            if not isinstance(self._zoo.server, SyncServer):
                with self._wid_lock:
                    slot = int(msg.src)
                    conn = getattr(msg, "_conn", None)
                    if conn is not None and self._leased.get(slot) is conn:
                        del self._leased[slot]
                        self._free_slots.append(slot)
                    else:
                        log.error("remote: ignoring deregister for slot %d "
                                  "(not leased to this connection)", slot)
            return
        if msg.type == MsgType.Server_Finish_Train:
            self._zoo.server.send(Message(
                src=msg.src, dst=-1, type=msg.type, table_id=msg.table_id,
                msg_id=msg.msg_id))
            return
        if msg.type not in (MsgType.Request_Get, MsgType.Request_Add):
            log.error("remote server: unhandled frame type %s", msg.type)
            return
        request = wire.decode(msg.data)
        completion = _NetCompletion(self._net, msg._conn, msg, compress)
        self._zoo.server.send(Message(
            src=msg.src, dst=-1, type=msg.type, table_id=msg.table_id,
            msg_id=msg.msg_id, data=[request, completion]))

    def _register_client(self, msg: Message) -> None:
        base = self._zoo.num_workers - self._zoo.remote_workers
        with self._wid_lock:
            if self._free_slots:
                worker_id = self._free_slots.pop()
                self._leased[worker_id] = msg._conn
            elif self._next_remote >= self._zoo.remote_workers:
                # refuse: an out-of-range worker id would alias slot-0
                # per-worker state and bypass the BSP clocks
                reply = Message(src=msg.dst, dst=msg.src,
                                type=MsgType.Control_Reply_Register,
                                msg_id=msg.msg_id,
                                data=wire.encode({"error": (
                                    f"all {self._zoo.remote_workers} remote "
                                    "worker slots are taken (raise the "
                                    "remote_workers flag at init)")}))
                self._net.send_via(msg._conn, reply)
                return
            else:
                worker_id = base + self._next_remote
                self._next_remote += 1
                self._leased[worker_id] = msg._conn
        directory = []
        # snapshot: create_table on the main thread mutates the dict
        for table_id, table in list(self._zoo.server._tables.items()):
            spec = table.remote_spec()
            if spec is not None:
                directory.append({"table_id": table_id, **spec})
        reply = Message(src=msg.dst, dst=msg.src,
                        type=MsgType.Control_Reply_Register,
                        msg_id=msg.msg_id,
                        data=wire.encode({"worker_id": worker_id,
                                          "num_workers": self._zoo.num_workers,
                                          "tables": directory}))
        self._net.send_via(msg._conn, reply)


# -- client side -------------------------------------------------------------

class RemoteChannel:
    """WorkerTable request channel that frames requests over TCP."""

    def __init__(self, client: "RemoteClient") -> None:
        self._client = client

    def worker_id(self) -> int:
        return self._client.worker_id

    def submit(self, table_id: int, msg_type: MsgType, request: Any,
               msg_id: int, completion: Completion) -> None:
        self._client._send(table_id, msg_type, request, msg_id, completion)

    def post(self, table_id: int, msg_type: MsgType) -> None:
        self._client._send(table_id, msg_type, None, next_msg_id(), None)


class RemoteClient:
    """Off-mesh table client: register → worker id + table directory."""

    def __init__(self, endpoint: str, timeout: float = 30.0) -> None:
        self._net = TcpNet()
        self._net.rank = -1
        self._net.connect([endpoint])
        self._pending: Dict[int, Completion] = {}
        self._lock = threading.Lock()
        self._compress = bool(config.get_flag("wire_compression"))
        self._pump_thread = threading.Thread(
            target=self._pump, daemon=True, name="mv-remote-client")
        self._pump_thread.start()
        self.worker_id = -1
        self.directory: List[Dict[str, Any]] = []
        self.num_workers = 0
        self._closed = False
        self._register(timeout)
        self._channel = RemoteChannel(self)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._net.send(Message(src=self.worker_id, dst=0,
                                   type=MsgType.Control_Deregister,
                                   msg_id=next_msg_id()))
        except OSError:
            pass  # server already gone; slot stays leased (static membership)
        self._net.finalize()

    def _register(self, timeout: float) -> None:
        msg_id = next_msg_id()
        completion = Completion()
        with self._lock:
            self._pending[msg_id] = completion
        self._net.send(Message(src=-1, dst=0, type=MsgType.Control_Register,
                               msg_id=msg_id, data=wire.encode(None)))
        info = completion.wait(timeout)
        if "error" in info:
            self._net.finalize()
            raise RuntimeError(f"remote registration refused: {info['error']}")
        self.worker_id = int(info["worker_id"])
        self.num_workers = int(info["num_workers"])
        self.directory = info["tables"]

    # -- request path --------------------------------------------------------
    def _send(self, table_id: int, msg_type: MsgType, request: Any,
              msg_id: int, completion: Optional[Completion]) -> None:
        if completion is not None:
            with self._lock:
                self._pending[msg_id] = completion
        data = [] if request is None and msg_type not in (
            MsgType.Request_Get, MsgType.Request_Add) else wire.encode(
                request, compress=self._compress)
        self._net.send(Message(src=self.worker_id, dst=0, type=msg_type,
                               table_id=table_id, msg_id=msg_id, data=data))

    def _pump(self) -> None:
        while True:
            try:
                msg = self._net.recv()
            except ConnectionError:
                self._fail_all(ConnectionError("server connection lost"))
                continue
            if msg is None:
                self._fail_all(ConnectionError("remote client shut down"))
                return
            with self._lock:
                completion = self._pending.pop(msg.msg_id, None)
            if completion is None:
                continue
            try:
                if msg.type == MsgType.Reply_Error:
                    completion.fail(RuntimeError(
                        f"server-side failure: {wire.decode(msg.data)}"))
                elif msg.type == MsgType.Reply_Add:
                    completion.done(None)
                else:
                    completion.done(wire.decode(msg.data))
            except Exception as exc:  # noqa: BLE001 — a malformed reply must
                # fail its waiter, not kill the pump (which would hang every
                # later request forever)
                completion.fail(exc)

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for completion in pending:
            completion.fail(exc)

    # -- table proxies -------------------------------------------------------
    def table(self, table_id: int) -> WorkerTable:
        """Build the worker proxy matching the server table's directory
        entry. Proxies share all shaping code with the in-process workers."""
        spec = next((s for s in self.directory
                     if s["table_id"] == table_id), None)
        if spec is None:
            raise KeyError(f"no remotable table with id {table_id}; "
                           f"directory: {self.directory}")
        kind = spec["kind"]
        if kind == "array":
            return _RemoteArrayWorker(spec, table_id, self._channel)
        if kind == "matrix":
            return _RemoteMatrixWorker(spec, table_id, self._channel)
        if kind == "kv":
            return _RemoteKVWorker(spec, table_id, self._channel)
        if kind == "sparse":
            return _RemoteSparseWorker(spec, table_id, self._channel)
        raise KeyError(f"unknown remote table kind {kind!r}")

    def tables(self) -> List[WorkerTable]:
        return [self.table(s["table_id"]) for s in self.directory]


def _make_error_feedback(shape, dtype) -> Optional[Any]:
    """Per-proxy ErrorFeedback when -wire_quant_bits is set (float32
    tables only — quantization targets gradient-delta payloads)."""
    bits = int(config.get_flag("wire_quant_bits"))
    if bits <= 0 or np.dtype(dtype) != np.float32:
        return None
    from multiverso_tpu.utils.quantization import ErrorFeedback
    return ErrorFeedback(shape, bits)


class _RemoteArrayWorker(ArrayWorker):
    """ArrayWorker shaping over the wire (no server construction)."""

    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.size = int(spec["size"])
        self.dtype = np.dtype(spec["dtype"])
        self._ef = _make_error_feedback((self.size,), self.dtype)

    def _submit(self, msg_type, request):
        # quantize ADD deltas on the way out (error feedback keeps the
        # lost precision in the client residual) — the server decodes to
        # plain float32 before process_add
        if (self._ef is not None and msg_type == MsgType.Request_Add
                and isinstance(request, tuple) and len(request) >= 2
                and isinstance(request[0], np.ndarray)
                and request[0].dtype == np.float32):
            request = (self._ef.compress(request[0]),) + request[1:]
        return super()._submit(msg_type, request)

    # device IO is in-process only (a remote hop IS a host hop); without
    # this override the class attribute inherited from ArrayWorker would
    # send per-leaf device requests over TCP
    supports_device_io = False

    def get_device(self):
        raise RuntimeError("get_device() needs mesh residency; remote "
                           "clients are off-mesh — use get()")

    def get_device_async(self, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "get/get_async (host arrays)")

    def add_device_async(self, delta, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def sync_leaves_async(self, delta_leaves, option=None, last_leaves=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def push_leaves_async(self, new_leaves, last_leaves, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def get_leaves_async(self, template_leaves, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "get/get_async (host arrays)")


class _RemoteMatrixWorker(MatrixWorker):
    """MatrixWorker shaping (row buckets, sparse cache, option defaults)
    over the wire. Device IO is in-process only (the whole point is
    skipping the host hop; a remote hop IS a host hop) — callers branch on
    ``supports_device_io``."""

    supports_device_io = False

    def get_device_async(self, row_ids, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "get/get_async (host arrays)")

    def transact_device_async(self, fn, others, args=(), touched=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def add_device_async(self, values, row_ids, option=None):
        log.fatal("device IO is in-process only; remote tables use "
                  "add/add_async (host arrays)")

    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.num_row = int(spec["num_row"])
        self.num_col = int(spec["num_col"])
        self.dtype = np.dtype(spec["dtype"])
        self._ef = _make_error_feedback((self.num_row, self.num_col),
                                        self.dtype)
        self.is_sparse = bool(spec.get("is_sparse", False))
        self._init_client_state(bool(spec.get("is_pipelined", False)),
                                int(spec.get("num_workers", 1)))

    def _submit(self, msg_type, request):
        # quantize row-delta ADDs with per-row error feedback (whole-table
        # adds use ids=None -> full-shape residual)
        if (self._ef is not None and msg_type == MsgType.Request_Add
                and isinstance(request, tuple) and len(request) == 3
                and isinstance(request[1], np.ndarray)
                and request[1].dtype == np.float32):
            ids, values, option = request
            if ids is not None:
                # pre-aggregate duplicate ids so every touched row's
                # residual is read and written exactly once — duplicates
                # would otherwise share one residual read and last-write
                # the update, permanently losing part of the feedback
                id_arr = np.asarray(ids)
                uniq, inverse = np.unique(id_arr, return_inverse=True)
                if len(uniq) != len(id_arr):
                    merged = np.zeros((len(uniq),) + values.shape[1:],
                                      values.dtype)
                    np.add.at(merged, inverse, values)
                    ids = uniq.astype(id_arr.dtype, copy=False)
                    values = merged
            request = (ids, self._ef.compress(values, ids), option)
        return super()._submit(msg_type, request)

    def get_device(self):
        raise RuntimeError("get_device() needs mesh residency; remote "
                           "clients are off-mesh — use get()")


class _RemoteKVWorker(KVWorker):
    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.value_dtype = np.dtype(spec["dtype"])
        self._raw: Dict[int, Any] = {}


class _RemoteSparseWorker(SparseWorker):
    """Sparse-key table shaping (O(nnz) get/add, counters) over the wire."""

    def __init__(self, spec, table_id: int, channel: RemoteChannel) -> None:
        WorkerTable.__init__(self, channel=channel)
        self.table_id = table_id
        self.key_space = int(spec["key_space"])
        self.width = int(spec["width"])
        self.dtype = np.dtype(spec["dtype"])
        self.elements_pushed = 0
        self.elements_pulled = 0
