"""Node/Role metadata (reference: ``include/multiverso/node.h:6-27``)."""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Role(enum.IntFlag):
    NONE = 0
    WORKER = 1
    SERVER = 2
    ALL = 3

    @classmethod
    def from_string(cls, text: str) -> "Role":
        table = {
            "none": cls.NONE,
            "worker": cls.WORKER,
            "server": cls.SERVER,
            "default": cls.ALL,
            "all": cls.ALL,
        }
        try:
            return table[text.strip().lower()]
        except KeyError:
            raise ValueError(f"unknown ps_role: {text!r}") from None


@dataclass
class Node:
    rank: int = 0
    role: Role = Role.ALL
    worker_id: int = -1
    server_id: int = -1

    @property
    def is_worker(self) -> bool:
        return bool(self.role & Role.WORKER)

    @property
    def is_server(self) -> bool:
        return bool(self.role & Role.SERVER)
