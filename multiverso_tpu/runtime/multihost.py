"""Cross-process (multi-host) lockstep PS runtime.

Reference capability (not copied): the reference scaled its parameter
server by adding MPI/ZMQ ranks — tables were range-sharded across server
ranks, each running its own Server actor, and ``RegisterNode`` grew the
membership (``src/zoo.cpp:73-145``, ``include/multiverso/net/mpi_net.h``).

TPU-native re-design: the table mesh spans every JAX process's devices
(multi-controller SPMD under ``jax.distributed``); ONE jitted op updates
the whole globally-sharded table and XLA's collectives move the bytes
over ICI/DCN. What MPI message ordering did for the reference, LOCKSTEP
REPLAY does here: rank 0 (the leader) runs the real dispatcher
(async / BSP / deterministic — all consistency logic lives there only)
and broadcasts each device-executing request descriptor over a tiny TCP
control plane; follower ranks replay the identical stream, so every
process issues the same collective program in the same order — the
multi-controller contract. Control traffic is ids + host payloads; table
bytes never cross TCP.

Completion routing:

* follower worker GETs complete at REPLAY time on the origin rank with
  the locally-materialized (replicated-out) result — the payload rides
  ICI, not TCP;
* follower worker ADDs complete via a small ``ack`` from the leader at
  whatever point the leader's server semantics complete them (enqueue
  for deferred-apply servers, apply otherwise), preserving each server
  type's contract.

Request payloads must be host data (numpy / options); the device-IO fast
paths are in-process-only and are disabled on every rank in multihost
mode (``supports_device_io`` is False on the table proxies).
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from multiverso_tpu import config, log
from multiverso_tpu.runtime.message import Message, MsgType

# flags: multihost_endpoint / multihost_timeout (defined in config.py so
# they exist before this module is first imported)

_LEN = struct.Struct("<q")


def _send_obj(sock: socket.socket, lock: threading.Lock, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    with lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_obj(sock: socket.socket) -> Any:
    header = _read_exact(sock, _LEN.size)
    if header is None:
        return None
    n = _LEN.unpack(header)[0]
    body = _read_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class _Forwarded:
    """A follower-origin request riding through the leader's server: the
    origin/msg_id pair travels WITH the request so deferred servers
    (BSP/deterministic) keep it attached through their pending queues and
    the lockstep wrapper can stamp it onto the broadcast descriptor."""

    __slots__ = ("origin", "msg_id", "request")

    def __init__(self, origin: int, msg_id: int, request: Any) -> None:
        self.origin = origin
        self.msg_id = msg_id
        self.request = request


class _ForwardCompletion:
    """Leader-side completion for a follower-origin request.

    ADDs ack over TCP at the moment the leader's server completes them —
    enqueue-time for deferred-apply servers, apply-time otherwise — so
    each server type's add contract survives the process hop. GET
    results are NOT shipped: the origin rank materializes the identical
    value itself when it replays the op (data rides ICI)."""

    __slots__ = ("_runtime", "_origin", "_msg_id", "_is_add")

    def __init__(self, runtime: "MultihostRuntime", origin: int,
                 msg_id: int, is_add: bool) -> None:
        self._runtime = runtime
        self._origin = origin
        self._msg_id = msg_id
        self._is_add = is_add

    def done(self, result: Any) -> None:
        if not self._is_add:
            return  # origin completes at replay with the local result
        if result is not None and not _is_host_payload(result):
            log.error("multihost: dropping non-host fused add reply "
                      "(device payloads cannot cross the control plane)")
            result = None
        self._runtime._send_to(self._origin, ("ack", self._msg_id, result))

    def fail(self, error: BaseException) -> None:
        self._runtime._send_to(self._origin,
                               ("fail", self._msg_id, repr(error)))


class _NullSink:
    """Write-discarding stream for follower-side snapshot replay (avoids
    buffering a full table copy nobody reads)."""

    def write(self, data: bytes) -> int:
        return len(data)


def _is_host_payload(obj: Any) -> bool:
    import numpy as np
    if obj is None or isinstance(obj, (int, float, str, bytes, np.ndarray)):
        return True
    if isinstance(obj, (tuple, list)):
        return all(_is_host_payload(x) for x in obj)
    return False


class LockstepTable:
    """Leader-side ServerTable wrapper: broadcast-then-execute.

    Registered in the leader's server in place of the inner table, so
    EVERY device-executing path (direct applies, BSP drains,
    deterministic round drains, admin reads, checkpoint stores) emits a
    descriptor before it runs — the one invariant multi-controller SPMD
    needs."""

    def __init__(self, inner: Any, runtime: "MultihostRuntime") -> None:
        self._inner = inner
        self._runtime = runtime

    # table_id assignment flows through to the inner table
    @property
    def table_id(self) -> int:
        return self._inner.table_id

    @table_id.setter
    def table_id(self, value: int) -> None:
        self._inner.table_id = value

    def process_add(self, request: Any) -> Any:
        origin, msg_id, request = self._split(request)
        if (isinstance(request, tuple) and request
                and isinstance(request[0], str) and request[0] == "transact"):
            log.fatal("device transactions are in-process only; multihost "
                      "tables take the staged host path")
        self._runtime.broadcast_exec("add", self.table_id, origin, msg_id,
                                     request)
        return self._inner.process_add(request)

    def process_get(self, request: Any) -> Any:
        origin, msg_id, request = self._split(request)
        self._runtime.broadcast_exec("get", self.table_id, origin, msg_id,
                                     request)
        return self._inner.process_get(request)

    def store(self, stream) -> None:
        """Snapshot through the DISPATCHER: the device->host read is a
        collective, so it must be serialized into the lockstep stream —
        checkpoint threads cannot broadcast+execute themselves without
        racing table traffic. The callable below runs on the dispatcher
        thread: broadcast, then read; followers replay the identical
        collective into a discarded sink."""
        def run():
            self._runtime.broadcast_exec("store", self.table_id, -1, 0,
                                         None)
            self._inner.store(stream)

        self._runtime.run_on_dispatcher(run)

    def load(self, stream) -> None:
        """Restore through the dispatcher: the leader reads the whole
        per-table checkpoint frame and broadcasts the BYTES, so every
        process rebuilds identical device state in lockstep order (safe
        even against live traffic — the dispatcher serializes it)."""
        payload = stream.read(-1)

        def run():
            self._runtime.broadcast_exec("load", self.table_id, -1, 0,
                                         payload)
            self._inner.load(io.BytesIO(payload))

        self._runtime.run_on_dispatcher(run)

    @staticmethod
    def _split(request: Any) -> Tuple[int, int, Any]:
        if isinstance(request, _Forwarded):
            return request.origin, request.msg_id, request.request
        return -1, 0, request

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class FollowerServer:
    """``Zoo.server`` stand-in on follower ranks: forwards local worker
    requests to the leader and replays the leader's lockstep stream on a
    single replay thread (the only thread that touches the mesh)."""

    def __init__(self, runtime: "MultihostRuntime") -> None:
        self._runtime = runtime
        self._tables: Dict[int, Any] = {}
        # the leader's server semantics, recomputed from the (identical)
        # flags — clients consult these capability bits
        self.gates_gets = (bool(config.get_flag("sync"))
                           or int(config.get_flag("ssp_staleness")) >= 0)
        self.defers_adds = (not self.gates_gets
                            and bool(config.get_flag("deterministic")))

    @property
    def plain_async(self) -> bool:
        # device transactions are in-process-only regardless of the
        # leader's server type
        return False

    def start(self) -> None:
        self._runtime.start_follower(self)

    def stop(self) -> None:
        pass  # the runtime owns the replay thread; Zoo.stop closes it

    def register_table(self, server_table: Any) -> int:
        table_id = len(self._tables)
        # stamp before visibility — replayed descriptors reference the id
        # the moment the leader-side registration barrier releases
        server_table.table_id = table_id
        self._tables[table_id] = server_table
        return table_id

    def table(self, table_id: int) -> Any:
        return self._tables[table_id]

    def send(self, msg: Message) -> None:
        completion = msg.data[-1] if msg.data else None
        request = msg.data[0] if msg.data else None
        if completion is not None:
            self._runtime.register_pending(msg.msg_id, completion)
        self._runtime.send_to_leader(
            ("req", int(msg.type), msg.table_id, msg.src, msg.msg_id,
             request))

    # replay executor ------------------------------------------------------
    def execute(self, op: str, table_id: int, origin: int, msg_id: int,
                request: Any) -> None:
        mine = origin == self._runtime.rank
        try:
            table = self._tables[table_id]
            if op == "add":
                result = table.process_add(request)
            elif op == "get":
                result = table.process_get(request)
            elif op == "store":
                # only the collective (device->host read) matters here;
                # the bytes go to a null sink — the leader owns the file
                table.store(_NullSink())
                result = None
            elif op == "load":
                table.load(io.BytesIO(request))
                result = None
            else:
                log.fatal("multihost replay: unknown op %r", op)
        except Exception as exc:
            log.error("multihost replay %s on table %d failed: %r", op,
                      table_id, exc)
            if mine:
                self._runtime.fail_pending(msg_id, exc)
            return
        if mine and op == "get":
            self._runtime.complete_pending(msg_id, result)


class MultihostRuntime:
    """Control plane: leader accept/forward loops, follower replay loop,
    broadcast ordering, cross-process barrier."""

    def __init__(self, rank: int, world: int, endpoint: str) -> None:
        self.rank = rank
        self.world = world
        self._endpoint = endpoint
        self._timeout = float(config.get_flag("multihost_timeout"))
        self._seq = 0
        self._stopping = threading.Event()
        # follower-side: outstanding local requests
        self._pending: Dict[int, Any] = {}
        self._pending_lock = threading.Lock()
        # leader-side: follower sockets by rank
        self._conns: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._threads: List[threading.Thread] = []
        self._barrier_arrivals = 0
        self._barrier_cv = threading.Condition()
        self._barrier_release = threading.Event()
        self._server: Optional[Any] = None        # leader: real Server
        self._follower: Optional[FollowerServer] = None
        self._leader_sock: Optional[socket.socket] = None
        self._leader_lock = threading.Lock()

    # -- bring-up ----------------------------------------------------------
    def connect(self) -> None:
        host, port = self._endpoint.rsplit(":", 1)
        if self.rank == 0:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((host, int(port)))
            listener.listen(self.world)
            listener.settimeout(self._timeout)
            while len(self._conns) < self.world - 1:
                try:
                    conn, _addr = listener.accept()
                except TimeoutError:
                    missing = sorted(set(range(1, self.world))
                                     - set(self._conns))
                    log.fatal("multihost: follower rank(s) %s never "
                              "connected to %s within %.0fs", missing,
                              self._endpoint, self._timeout)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # bound the hello read too: an accepted connection that
                # never speaks (scanner, half-dead follower) must not
                # wedge bring-up past the configured timeout
                conn.settimeout(self._timeout)
                try:
                    hello = _recv_obj(conn)
                except (OSError, pickle.UnpicklingError):
                    hello = None
                if not (isinstance(hello, tuple) and len(hello) == 2
                        and hello[0] == "hello"):
                    log.error("multihost: dropping connection with bad "
                              "handshake %r", hello)
                    conn.close()
                    continue
                peer = int(hello[1])
                if not 1 <= peer < self.world or peer in self._conns:
                    log.fatal("multihost: follower handshake claims rank "
                              "%d (world %d, already connected: %s)",
                              peer, self.world, sorted(self._conns))
                conn.settimeout(None)
                self._conns[peer] = conn
                self._send_locks[peer] = threading.Lock()
            listener.close()
            for peer, conn in self._conns.items():
                t = threading.Thread(target=self._leader_recv_loop,
                                     args=(peer, conn),
                                     name=f"mv-multihost-recv-{peer}",
                                     daemon=True)
                t.start()
                self._threads.append(t)
        else:
            import time
            deadline = time.monotonic() + self._timeout
            sock = None
            while True:
                try:
                    sock = socket.create_connection(
                        (host, int(port)),
                        timeout=max(1.0, deadline - time.monotonic()))
                    break
                except OSError:
                    # the leader may not have bound yet — retry until the
                    # handshake window closes
                    if time.monotonic() >= deadline:
                        log.fatal("multihost: cannot reach leader at %s "
                                  "within %.0fs", self._endpoint,
                                  self._timeout)
                    time.sleep(0.1)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            self._leader_sock = sock
            _send_obj(sock, self._leader_lock, ("hello", self.rank))

    def attach_leader(self, server: Any) -> None:
        self._server = server

    def wrap_table(self, server_table: Any) -> LockstepTable:
        return LockstepTable(server_table, self)

    def start_follower(self, follower: FollowerServer) -> None:
        self._follower = follower
        t = threading.Thread(target=self._replay_loop,
                             name="mv-multihost-replay", daemon=True)
        t.start()
        self._threads.append(t)

    # -- leader side -------------------------------------------------------
    def run_on_dispatcher(self, fn: Any) -> Any:
        """Execute ``fn`` on the leader's dispatcher thread, serialized
        with table traffic (delegates to Server.run_serialized — the
        shared quiesced-execution primitive; re-entrant)."""
        return self._server.run_serialized(fn, timeout=self._timeout)

    def broadcast_exec(self, op: str, table_id: int, origin: int,
                       msg_id: int, request: Any) -> None:
        """Emit one lockstep descriptor to every follower. Must run on
        the leader's dispatcher thread — that single thread's execution
        order IS the collective program order every process must share;
        a broadcast from any other thread could interleave differently
        with the leader's own executions."""
        expected = getattr(self._server, "_thread", None)
        if expected is not None and threading.current_thread() is not expected:
            log.fatal("multihost: broadcast_exec off the dispatcher thread "
                      "(%s) — route through run_on_dispatcher",
                      threading.current_thread().name)
        # pickle BEFORE consuming a sequence number: a non-serializable
        # request must fail only itself, not desync every follower's
        # expected seq (the fatal propagates to the requester's completion
        # via Server._main; the lockstep stream stays consistent)
        desc = ("exec", self._seq + 1, op, table_id, origin, msg_id, request)
        try:
            payload = pickle.dumps(desc, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as exc:
            log.fatal("multihost: request is not host-serializable (%r) — "
                      "device-array payloads cannot cross processes; use "
                      "the host add/get paths", exc)
        self._seq += 1
        framed = _LEN.pack(len(payload)) + payload
        for peer in sorted(self._conns):
            sock = self._conns[peer]
            try:
                with self._send_locks[peer]:
                    sock.sendall(framed)
            except OSError as exc:
                # a peer that missed a descriptor can never rejoin the
                # stream — drop it loudly; its absence surfaces at the
                # next collective (Gloo) rather than as silent corruption
                log.error("multihost: lost follower %d mid-broadcast (%r);"
                          " dropping it from the control plane", peer, exc)
                self._conns.pop(peer, None)

    def _leader_recv_loop(self, peer: int, conn: socket.socket) -> None:
        while True:
            obj = _recv_obj(conn)
            if obj is None:
                if not self._stopping.is_set():
                    log.error("multihost: lost follower %d", peer)
                return
            kind = obj[0]
            if kind == "req":
                _, msg_type, table_id, src, msg_id, request = obj
                msg_type = MsgType(msg_type)
                data: List[Any] = []
                if msg_type.is_server_bound and msg_type in (
                        MsgType.Request_Add, MsgType.Request_Get):
                    completion = _ForwardCompletion(
                        self, peer, msg_id,
                        is_add=msg_type == MsgType.Request_Add)
                    data = [_Forwarded(peer, msg_id, request), completion]
                self._server.send(Message(
                    src=src, dst=-1, type=msg_type, table_id=table_id,
                    msg_id=msg_id, data=data))
            elif kind == "barrier_enter":
                with self._barrier_cv:
                    self._barrier_arrivals += 1
                    self._barrier_cv.notify_all()
            elif kind == "bye":
                return
            else:
                log.error("multihost: unknown message %r from %d", kind,
                          peer)

    def _send_to(self, peer: int, obj: Any) -> None:
        if peer < 0:
            return
        sock = self._conns.get(peer)
        if sock is None:
            return
        try:
            _send_obj(sock, self._send_locks[peer], obj)
        except OSError as exc:
            log.error("multihost: send to %d failed: %r", peer, exc)

    # -- follower side -----------------------------------------------------
    def send_to_leader(self, obj: Any) -> None:
        _send_obj(self._leader_sock, self._leader_lock, obj)

    def register_pending(self, msg_id: int, completion: Any) -> None:
        with self._pending_lock:
            self._pending[msg_id] = completion

    def complete_pending(self, msg_id: int, result: Any) -> None:
        with self._pending_lock:
            completion = self._pending.pop(msg_id, None)
        if completion is not None:
            completion.done(result)

    def fail_pending(self, msg_id: int, exc: BaseException) -> None:
        with self._pending_lock:
            completion = self._pending.pop(msg_id, None)
        if completion is not None:
            completion.fail(exc if isinstance(exc, Exception)
                            else RuntimeError(repr(exc)))

    def _replay_loop(self) -> None:
        expect_seq = 0
        while True:
            obj = _recv_obj(self._leader_sock)
            if obj is None:
                if not self._stopping.is_set():
                    log.error("multihost: lost leader connection")
                return
            kind = obj[0]
            if kind == "exec":
                _, seq, op, table_id, origin, msg_id, request = obj
                expect_seq += 1
                if seq != expect_seq:
                    log.fatal("multihost replay out of order: seq %d, "
                              "expected %d — collective stream corrupt",
                              seq, expect_seq)
                self._follower.execute(op, table_id, origin, msg_id,
                                       request)
            elif kind == "ack":
                self.complete_pending(obj[1], obj[2])
            elif kind == "fail":
                self.fail_pending(obj[1], RuntimeError(obj[2]))
            elif kind == "barrier_release":
                self._barrier_release.set()
            elif kind == "stop":
                self._stopping.set()
                return
            else:
                log.error("multihost: unknown descriptor %r", kind)

    # -- barrier -----------------------------------------------------------
    def barrier(self) -> None:
        """Cross-process rendezvous over the control plane (the analog of
        the reference Controller's Barrier message round,
        ``src/controller.cpp:82-107``)."""
        if self.rank == 0:
            with self._barrier_cv:
                if not self._barrier_cv.wait_for(
                        lambda: self._barrier_arrivals >= self.world - 1,
                        timeout=self._timeout):
                    log.fatal("multihost barrier timed out "
                              "(%d/%d followers arrived)",
                              self._barrier_arrivals, self.world - 1)
                self._barrier_arrivals -= self.world - 1
            for peer in sorted(self._conns):
                self._send_to(peer, ("barrier_release",))
        else:
            self._barrier_release.clear()
            self.send_to_leader(("barrier_enter", self.rank))
            if not self._barrier_release.wait(self._timeout):
                log.fatal("multihost barrier timed out waiting for release")

    # -- teardown ----------------------------------------------------------
    def shutdown(self) -> None:
        self._stopping.set()
        if self.rank == 0:
            for peer in sorted(self._conns):
                self._send_to(peer, ("stop",))
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        else:
            try:
                self.send_to_leader(("bye",))
            except OSError:
                pass
            # let the replay thread consume the leader's "stop" so no
            # lockstep descriptor is dropped mid-collective
            for t in self._threads:
                t.join(timeout=self._timeout)
            if self._leader_sock is not None:
                try:
                    self._leader_sock.close()
                except OSError:
                    pass
                self._leader_sock = None


def spawn_lockstep_world(child_script: str, scenario: str, world: int = 2,
                         devices_per_proc: int = 4,
                         timeout: float = 300.0,
                         expect: Optional[Dict[int, Tuple[int,
                                                          Optional[str]]]]
                         = None) -> List[str]:
    """Launch ``world`` OS processes running ``child_script`` (rank, world,
    coordinator port, control port, scenario argv) with per-process virtual
    CPU devices — the shared harness behind tests/test_multihost.py and
    __graft_entry__.dryrun_multichip's multiprocess leg. Returns each
    rank's combined output; raises RuntimeError on any failure or missing
    OK marker. ``expect`` overrides the (returncode, required-marker)
    expectation per rank — ``(42, None)`` accepts a deliberately-crashed
    rank (failure-injection scenarios)."""
    import os
    import subprocess
    import sys

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    coord, ctl = free_port(), free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{devices_per_proc}")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("_MV_DRYRUN_CHILD", None)
    procs = [
        subprocess.Popen(
            [sys.executable, child_script, str(rank), str(world),
             str(coord), str(ctl), scenario],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo)
        for rank in range(world)
    ]
    outs: List[str] = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        want_rc, want_marker = (expect or {}).get(
            rank, (0, f"MULTIHOST_CHILD_OK rank={rank}"))
        if p.returncode != want_rc or (want_marker is not None
                                       and want_marker not in out):
            raise RuntimeError(f"lockstep world rank {rank} failed "
                               f"(rc={p.returncode}, want {want_rc} with "
                               f"{want_marker!r}):\n{out}")
    return outs
